"""r22 closed-observability-loop suite (``obs/rules.py``,
``obs/controller.py``, the flight-recorder scopes, the rank-restart
rejoin path, and the serve/forward seams the controller drives).

Covers: rule hysteresis (hold windows, hysteresis bands, the
self-calibrating spike mode, cross-rank skew, staleness), alert-record
determinism, controller dispatch (drain + effect probe, DGRO re-score,
resize) with span parentage reconstructable via ``obs.chain()``,
per-scope flight dumps (a failing mitigation must NOT burn the
once-per-process engine crash dump), the LiveOps kill-and-rejoin
stale→live transition over a LocalKV twin, the RingStore drain/rescore
generation commits, and ``forward.batch.rank_load`` (the skew signal).
"""

import json
import threading
import time

import numpy as np
import pytest

from ringpop_tpu.obs import trace as tracemod
from ringpop_tpu.obs.aggregate import AggregatingStats, render_prometheus
from ringpop_tpu.obs.controller import OpsController
from ringpop_tpu.obs.endpoint import LiveOps
from ringpop_tpu.obs.flight import FlightRecorder
from ringpop_tpu.obs.rules import (
    FLEET,
    CrossRankSkew,
    RateOfChange,
    RuleEngine,
    Staleness,
    Threshold,
)
from ringpop_tpu.parallel.fabric import LocalKV


def _gauges(**kv) -> dict:
    return {"gauges": dict(kv)}


def _counters(**kv) -> dict:
    return {"counters": dict(kv)}


# -- rule hysteresis ----------------------------------------------------------


def test_threshold_hold_window_and_band():
    out = []
    eng = RuleEngine(
        [Threshold(id="hot", key="g", op=">", firing=10.0, clear=5.0,
                   hold=2, hold_clear=2)],
        sink=out.append,
    )
    # one hot evaluation is not enough (hold=2)
    assert eng.evaluate({0: _gauges(g=12.0)}) == []
    fired = eng.evaluate({0: _gauges(g=13.0)})
    assert [r["state"] for r in fired] == ["firing"]
    assert fired[0]["rule"] == "hot" and fired[0]["about_rank"] == FLEET
    assert fired[0]["kind"] == "alert" and fired[0]["parent"] is None
    # inside the hysteresis band (5 < v <= 10): neither clears nor refires
    assert eng.evaluate({0: _gauges(g=7.0)}) == []
    assert eng.state("hot") is True
    # below the clear edge, but hold_clear=2 needs two in a row
    assert eng.evaluate({0: _gauges(g=3.0)}) == []
    cleared = eng.evaluate({0: _gauges(g=3.0)})
    assert [r["state"] for r in cleared] == ["clear"]
    assert eng.state("hot") is False
    # the clear shares its firing's trace: one chain() pulls the episode
    assert cleared[0]["trace"] == fired[0]["trace"]
    assert out == fired + cleared and eng.alerts_emitted == 2


def test_alert_spans_are_rerun_deterministic():
    def run():
        eng = RuleEngine(
            [Threshold(id="hot", key="g", firing=1.0)], sink=lambda r: None
        )
        recs = []
        for v in (2.0, 0.0, 2.0):
            recs.extend(eng.evaluate({0: _gauges(g=v)}))
        return [(r["trace"], r["span"], r["state"]) for r in recs]

    first, second = run(), run()
    assert first == second and len(first) == 3
    # the second firing is a NEW episode: distinct trace from the first
    assert first[0][0] != first[2][0]


def test_rate_of_change_spike_mode_self_calibrating():
    eng = RuleEngine(
        [RateOfChange(id="spike", key="c", spike_ratio=4.0, floor=1.0,
                      per_rank=False, hold=1)],
        sink=lambda r: None,
    )
    # baseline deltas of 10/eval: obs #1 has no delta, #2 no prev delta,
    # #3 is the first ratio (1.0 — quiet)
    for v in (0.0, 10.0, 20.0):
        assert eng.evaluate({0: _counters(c=v)}) == []
    # a 8x step in the delta fires regardless of the absolute level
    fired = eng.evaluate({0: _counters(c=100.0)})
    assert [r["state"] for r in fired] == ["firing"]
    assert fired[0]["value"] == pytest.approx(8.0)
    # back to baseline: ratio collapses, the alert clears
    cleared = eng.evaluate({0: _counters(c=110.0)})
    assert [r["state"] for r in cleared] == ["clear"]


def test_rate_of_change_stall_band():
    eng = RuleEngine(
        [RateOfChange(id="stall", key="c", low=1.0, per_rank=True, hold=1)],
        sink=lambda r: None,
    )
    assert eng.evaluate({1: _counters(c=100.0)}) == []
    assert eng.evaluate({1: _counters(c=110.0)}) == []  # delta 10: fine
    fired = eng.evaluate({1: _counters(c=110.0)})  # delta 0: stalled
    assert [(r["state"], r["about_rank"]) for r in fired] == [("firing", 1)]


def test_cross_rank_skew_names_the_skewed_rank():
    eng = RuleEngine(
        [CrossRankSkew(id="skew", key="load", ratio=1.5, hold=1)],
        sink=lambda r: None,
    )
    # one rank reporting -> below min_ranks, no observation at all
    assert eng.evaluate({0: _gauges(load=10.0)}) == []
    fired = eng.evaluate({0: _gauges(load=10.0), 1: _gauges(load=40.0)})
    assert [(r["state"], r["about_rank"]) for r in fired] == [("firing", 1)]
    assert fired[0]["value"] == pytest.approx(40.0 / 25.0)
    balanced = {0: _gauges(load=24.0), 1: _gauges(load=26.0)}
    cleared = eng.evaluate(balanced)
    assert [(r["state"], r["about_rank"]) for r in cleared] == [("clear", 1)]


def test_staleness_skips_self_and_holds():
    eng = RuleEngine([Staleness(id="stale", hold=2)], sink=lambda r: None)
    health = {"ranks": {
        "0": {"live": True, "self": True},
        "1": {"live": False},
    }}
    assert eng.evaluate({}, health=health) == []
    fired = eng.evaluate({}, health=health)
    assert [(r["state"], r["about_rank"]) for r in fired] == [("firing", 1)]
    # the self entry never becomes a subject
    assert eng.state("stale", 0) is None


def test_engine_isolates_broken_rules_and_rejects_dup_ids():
    class Broken(Threshold):
        def observe(self, ctx):
            raise RuntimeError("boom")

    eng = RuleEngine(
        [Broken(id="bad", key="g", firing=0.0),
         Threshold(id="good", key="g", firing=1.0)],
        sink=lambda r: None,
    )
    fired = eng.evaluate({0: _gauges(g=5.0)})
    assert [r["rule"] for r in fired] == ["good"]
    with pytest.raises(ValueError, match="duplicate rule ids"):
        RuleEngine(
            [Threshold(id="x", key="g"), Threshold(id="x", key="h")],
            sink=lambda r: None,
        )


def test_engine_counts_sink_failures_without_raising():
    def bad_sink(rec):
        raise OSError("disk gone")

    eng = RuleEngine([Threshold(id="t", key="g", firing=1.0)], sink=bad_sink)
    fired = eng.evaluate({0: _gauges(g=5.0)})
    assert len(fired) == 1  # the record still comes back to the caller
    assert eng.alerts_dropped == 1 and eng.alerts_emitted == 0


# -- controller dispatch + span parentage -------------------------------------


class _StubStore:
    def __init__(self, fail=False):
        self.fail = fail
        self.gen = 0
        self.drained = []
        self.rescored = 0

    def drain(self, servers):
        if self.fail:
            raise RuntimeError("ring wedged")
        self.gen += 1
        self.drained.extend(servers)
        return {"gen": self.gen, "removed": list(servers), "drain": True}

    def rescore_placement(self):
        self.gen += 1
        self.rescored += 1
        return {"gen": self.gen, "rescored": True,
                "placement": {"movement_chosen": 0.25}}


def _one_alert(journal, rule_id="spike", subject=FLEET):
    eng = RuleEngine(
        [RateOfChange(id=rule_id, key="c", spike_ratio=4.0, per_rank=False,
                      hold=1)],
        sink=journal.append,
    )
    for v in (0.0, 10.0, 20.0):
        eng.evaluate({0: _counters(c=v)})
    fired = eng.evaluate({0: _counters(c=200.0)})
    assert len(fired) == 1
    return fired


def test_controller_drain_effect_chain_reconstructs():
    journal: list[dict] = []
    store = _StubStore()
    ctl = OpsController(
        sink=journal.append,
        policy={"spike": "drain"},
        ring_store=store,
        server_of=lambda subject: "z0",
        drain_probe=lambda server: 0,
    )
    alerts = _one_alert(journal)
    acts = ctl.on_alerts(alerts, tick=24)
    assert [a["action"] for a in acts] == ["drain", "effect"]
    drain, effect = acts
    alert = alerts[0]
    # the action joins the ALERT's trace and parents on its span;
    # the effect parents on the action
    assert drain["trace"] == alert["trace"]
    assert drain["parent"] == alert["span"]
    assert effect["parent"] == drain["span"] and effect["of"] == "drain"
    assert drain["ok"] and drain["detail"] == {"server": "z0", "gen": 1}
    assert effect["ok"] and effect["detail"]["share"] == 0
    assert store.drained == ["z0"]
    # chain() over the raw journal: alert first, then action, then effect
    ch = tracemod.chain(journal, alert["trace"])
    assert [(r["kind"], r.get("action")) for r in ch] == [
        ("alert", None), ("action", "drain"), ("action", "effect"),
    ]
    # an already-drained subject does not re-drain (nor does cooldown
    # permit an immediate repeat)
    assert ctl.on_alerts(alerts, tick=25) == []
    assert store.gen == 1 and ctl.actions_taken == 1


def test_controller_ignores_clears_and_unpoliced_rules():
    journal: list[dict] = []
    ctl = OpsController(
        sink=journal.append, policy={"spike": "drain"},
        ring_store=_StubStore(), server_of=lambda s: "z0",
    )
    clear = [{"kind": "alert", "rule": "spike", "state": "clear",
              "about_rank": FLEET, "trace": 1, "span": 2}]
    other = [{"kind": "alert", "rule": "unmapped", "state": "firing",
              "about_rank": FLEET, "trace": 3, "span": 4}]
    assert ctl.on_alerts(clear, tick=1) == []
    assert ctl.on_alerts(other, tick=2) == []
    assert journal == [] and ctl.actions_taken == 0


def test_controller_dgro_rescore_and_resize_dispatch():
    journal: list[dict] = []
    store = _StubStore()
    resized = []

    def resize(rank):
        resized.append(rank)
        return {"target_p": 1}

    ctl = OpsController(
        sink=journal.append,
        policy={"skew": "dgro_rescore", "stale": "resize"},
        ring_store=store,
        resize=resize,
        cooldown=1,
    )
    skew = [{"kind": "alert", "rule": "skew", "state": "firing",
             "about_rank": 1, "trace": 11, "span": 12}]
    stale = [{"kind": "alert", "rule": "stale", "state": "firing",
              "about_rank": 1, "trace": 21, "span": 22}]
    a1 = ctl.on_alerts(skew, tick=8)
    a2 = ctl.on_alerts(stale, tick=16)
    assert [a["action"] for a in a1 + a2] == ["dgro_rescore", "resize"]
    assert a1[0]["ok"] and a1[0]["detail"]["placement"] == {
        "movement_chosen": 0.25
    }
    assert store.rescored == 1
    assert a2[0]["ok"] and a2[0]["detail"] == {"target_p": 1}
    assert resized == [1]
    assert ctl.actions_taken == 2 and len(journal) == 2
    with pytest.raises(ValueError, match="unknown actions"):
        OpsController(sink=journal.append, policy={"x": "reboot_the_moon"})


def test_controller_rejects_unknown_policy_subjects_cooldown_per_subject():
    journal: list[dict] = []
    ctl = OpsController(
        sink=journal.append, policy={"skew": "dgro_rescore"},
        ring_store=_StubStore(), cooldown=1000,
    )
    mk = lambda rank: [{  # noqa: E731
        "kind": "alert", "rule": "skew", "state": "firing",
        "about_rank": rank, "trace": rank * 10, "span": rank * 10 + 1,
    }]
    assert len(ctl.on_alerts(mk(1), tick=1)) == 1
    assert ctl.on_alerts(mk(1), tick=2) == []  # cooldown holds per subject
    assert len(ctl.on_alerts(mk(2), tick=3)) == 1  # other subject free


# -- failing mitigation: the controller's OWN flight scope --------------------


def test_failed_mitigation_dumps_controller_scope_only(tmp_path):
    rec = FlightRecorder(capacity=16, rank=0,
                         path=str(tmp_path / "flight.jsonl"))
    rec.event("warmup", n=1)
    journal: list[dict] = []
    ctl = OpsController(
        sink=journal.append, policy={"spike": "drain"},
        ring_store=_StubStore(fail=True), server_of=lambda s: "z0",
        recorder=rec, cooldown=1,
    )
    alerts = _one_alert(journal)
    acts = ctl.on_alerts(alerts, tick=24)
    assert len(acts) == 1 and not acts[0]["ok"]
    assert "RuntimeError: ring wedged" in acts[0]["error"]
    assert ctl.actions_failed == 1
    # exactly one dump, controller-scoped, naming the failed action —
    # and the ENGINE once-per-process slot is untouched
    ctl_dump = tmp_path / "flight-controller.jsonl"
    assert rec.dumps == {"controller": str(ctl_dump)}
    assert rec.dumped is None
    lines = ctl_dump.read_text().splitlines()
    header = json.loads(lines[0])
    assert header["kind"] == "flight_header"
    assert header["scope"] == "controller"
    assert header["reason"] == "controller:drain"
    assert "RuntimeError" in header["error"]
    # a second failing mitigation does not re-dump (once per scope)
    more = _one_alert(journal, subject=FLEET)
    ctl._drained.clear()
    ctl.on_alerts(more, tick=32)
    assert list(rec.dumps) == ["controller"]
    # the engine crash dump still fires afterwards, to its own file
    engine = rec.dump("fabric:FabricPeerLost", error=OSError("peer gone"))
    assert engine == str(tmp_path / "flight.jsonl")
    assert rec.dumped == engine
    eh = json.loads((tmp_path / "flight.jsonl").read_text().splitlines()[0])
    assert eh["scope"] == "engine" and eh["reason"] == "fabric:FabricPeerLost"


# -- /metrics timing exposition (satellite: real summaries) -------------------


def test_prometheus_timing_summary_exposition():
    st = AggregatingStats()
    for v in (0.010, 0.020, 0.030):
        st.timing("ringpop.serve.lookup-us", v)
    text = render_prometheus({0: st.snapshot()})
    assert "# TYPE ringpop_serve_lookup_us summary" in text
    # the reservoir caveat must ride the family, and no _sum may exist
    assert "reservoir-sampled quantiles" in text
    assert "ringpop_serve_lookup_us_sum" not in text
    assert 'ringpop_serve_lookup_us{rank="0",quantile="0.5"}' in text
    assert 'ringpop_serve_lookup_us{rank="0",quantile="0.99"}' in text
    assert 'ringpop_serve_lookup_us_count{rank="0"} 3' in text
    # aux stats stay available as explicit gauges
    assert "# TYPE ringpop_serve_lookup_us_mean gauge" in text


# -- LiveOps rank restart: stale -> live over the rejoin path -----------------


def _sync_until(opses, pred, rounds=300, pause=0.02):
    for _ in range(rounds):
        for ops in opses:
            ops.sync()
        if pred():
            return True
        time.sleep(pause)
    return False


def test_liveops_rank_restart_rejoins_same_rank_id():
    kv = LocalKV()
    ns = "obs-rejoin-t"
    built: dict[int, LiveOps] = {}

    def boot(rank):
        built[rank] = LiveOps(rank, 2, kv=kv, namespace=ns,
                              timeout_ms=10_000, stale_s=120.0)

    ts = [threading.Thread(target=boot, args=(r,)) for r in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(30)
    ops0, ops1 = built[0], built[1]
    ops1b = None
    try:
        assert _sync_until(
            [ops1, ops0],
            lambda: ops0.health()["ranks"].get("1", {}).get("live") is True,
        ), "initial bring-up never went live"

        # rank 1 dies abruptly: its socket closes, rank 0's pending
        # obs rounds fail, /healthz flips the rank to live=false
        ops1.close()
        assert _sync_until(
            [ops0],
            lambda: ops0.health()["ranks"]["1"]["live"] is False,
        ), "rank 0 never marked the dead rank stale"

        # the restart: SAME rank id, rejoin=True — the fabric advertises
        # a rejoin listener instead of redoing collective bring-up, and
        # rank 0 dials it from sync().  stale -> live is the pin.
        ops1b = LiveOps(1, 2, kv=kv, namespace=ns,
                        timeout_ms=10_000, stale_s=120.0, rejoin=True)
        assert _sync_until(
            [ops1b, ops0],
            lambda: ops0.health()["ranks"]["1"]["live"] is True,
        ), "restarted rank never transitioned back to live"

        # and the data plane works again: fresh counters flow to rank 0
        ops1b.stats.incr("ringpop.test.rejoin", 7)
        assert _sync_until(
            [ops1b, ops0],
            lambda: ops0.snapshots().get(1, {}).get("counters", {})
            .get("ringpop.test.rejoin") == 7,
        ), "restarted rank's snapshots never reached rank 0"
        assert ops0.health()["ok"] is True
    finally:
        for ops in (ops0, ops1, ops1b):
            if ops is not None:
                ops.close()


# -- the serve/forward seams the controller drives ----------------------------


def test_ring_store_drain_commit_and_record():
    from ringpop_tpu.serve.state import RingStore

    events: list[dict] = []
    store = RingStore(["z0", "z1", "z2", "z3"], replica_points=16,
                      on_update=events.append)
    g0 = store.gen
    rec = store.drain(["z1"])
    assert rec is not None and rec["gen"] == g0 + 1
    assert rec["drain"] is True and rec["removed"] == ["z1"]
    # the listener saw the SAME stamped record (stamped before on_update)
    assert events[-1]["drain"] is True
    # the drained server really routes away
    keys = [f"k{i}" for i in range(256)]
    assert "z1" not in set(store.ring.lookup_batch(keys))
    # draining a server that is not in the ring is a no-op
    assert store.drain(["nope"]) is None
    assert store.gen == g0 + 1


def test_ring_store_rescore_only_under_dgro():
    from ringpop_tpu.serve.state import RingStore

    plain = RingStore(["a", "b"], replica_points=8)
    assert plain.rescore_placement() is None

    events: list[dict] = []
    store = RingStore(
        ["a", "b", "c", "d"], replica_points=16, placement="dgro",
        placement_kw={"candidates": 2, "probes": 1 << 8},
        on_update=events.append,
    )
    g0 = store.gen
    rec = store.rescore_placement()
    assert rec is not None and rec["gen"] == g0 + 1
    assert rec["rescored"] is True
    # the fresh scorer report rides the record for the journal
    assert "placement" in rec and "movement_chosen" in rec["placement"]
    assert events[-1].get("rescored") is True


def test_rank_load_is_the_skew_signal():
    from ringpop_tpu.forward.batch import rank_load
    from ringpop_tpu.ops.ring_ops import build_ring_tokens

    toks, _ = build_ring_tokens([f"s{i}" for i in range(4)], 8)
    tokens = np.asarray(toks, np.uint32)
    rng = np.random.default_rng(7)
    hashes = rng.integers(0, 1 << 32, size=512, dtype=np.uint64).astype(
        np.uint32
    )
    loads = rank_load(tokens, hashes, 2)
    assert loads.shape == (2,) and loads.dtype == np.int64
    assert int(loads.sum()) == 512
    assert (loads > 0).all()  # 512 uniform keys never land one-sided
