"""Lockstep conformance: the vectorized fullview engine must be bit-identical
to the sequential reference interpreter under injected randomness
(the BASELINE "bit-identical member states vs sequential reference semantics"
gate; semantics parity ``swim/memberlist.go:310-390``, ``swim/node.go:470-513``,
``swim/state_transitions.go:90-117``)."""

import numpy as np
import pytest

from ringpop_tpu.sim.conformance import LockstepRunner
from ringpop_tpu.sim.fullview import Faults
from ringpop_tpu.swim.member import ALIVE, FAULTY, SUSPECT


class TestLockstepConformance:
    def test_stable_cluster(self):
        r = LockstepRunner(n=32, seed=1)
        r.run(30)

    def test_dead_nodes_full_lifecycle(self):
        # short timeouts so the whole suspect→faulty→tombstone→evict chain
        # plays out inside the run
        r = LockstepRunner(
            n=32, seed=2, suspect_ticks=4, faulty_ticks=8, tombstone_ticks=4
        )
        up = np.ones(32, bool)
        up[[3, 11, 19]] = False
        r.run(40, faults=Faults(up=np.asarray(up)))
        # sanity: the oracle actually detected the failures (not a vacuous run)
        seq_view = r.seq.nodes[0].view
        assert all(seq_view.get(d, (ALIVE, 0))[0] != ALIVE or d not in seq_view for d in (3, 11, 19))

    def test_kill_then_revive_refutation(self):
        r = LockstepRunner(n=24, seed=3, suspect_ticks=6)
        up = np.ones(24, bool)
        up[5] = False
        r.run(10, faults=Faults(up=np.asarray(up)))
        # someone detected node 5 by now (suspect, or already faulty)
        assert any(n.view.get(5, (ALIVE, 0))[0] != ALIVE for n in r.seq.nodes)
        up[5] = True
        r.run(25)
        # node 5 refuted: alive at a bumped incarnation everywhere it is known
        assert all(
            n.view[5][0] == ALIVE and n.view[5][1] > 0
            for n in r.seq.nodes
            if 5 in n.view
        )

    def test_partition_then_heal(self):
        n = 32
        r = LockstepRunner(n=n, seed=4, suspect_ticks=4, faulty_ticks=1000)
        group = np.zeros(n, np.int32)
        group[n // 2 :] = 1
        r.run(25, faults=Faults(group=np.asarray(group)))
        r.run(40)  # heal: full syncs + refutations reconverge the views

    def test_packet_level_asymmetry_via_groups(self):
        # three-way partition exercises inconclusive ping-req paths
        n = 30
        r = LockstepRunner(n=n, seed=5, suspect_ticks=3)
        group = np.asarray(np.arange(n) % 3, np.int32)
        r.run(20, faults=Faults(group=group))
        r.run(30)

    @pytest.mark.slow
    def test_midscale_conformance(self):
        # larger-N spot check
        r = LockstepRunner(n=128, seed=6, suspect_ticks=5, faulty_ticks=40, tombstone_ticks=10)
        up = np.ones(128, bool)
        up[::16] = False
        r.run(30, faults=Faults(up=np.asarray(up)), check_every=5)
        r.run(20, check_every=5)

    @pytest.mark.slow
    def test_1k_node_conformance_gate(self):
        """The BASELINE gate: bit-identical member states vs the sequential
        reference semantics at 1k nodes, through a kill + recovery cycle."""
        n = 1000
        r = LockstepRunner(n=n, seed=7, suspect_ticks=4, faulty_ticks=30, tombstone_ticks=8)
        up = np.ones(n, bool)
        up[[99, 499, 999]] = False
        r.run(12, faults=Faults(up=np.asarray(up)), check_every=4)
        r.run(8, check_every=4)
        r.assert_identical()
