"""Golden-trajectory regression for the delta engine — the dissemination
twin of ``test_lifecycle_golden.py``: every field of every tick must
reproduce bit-for-bit across representation changes (the packed
``learned`` plane included), PRNG draw order and all."""

from __future__ import annotations

import numpy as np
import pytest

from ringpop_tpu.sim import delta

from tests import golden_tools
from tests.capture_delta_golden import CONFIGS, GOLDEN_PATH, run_config
from tests.test_lifecycle_golden import _as_bool_plane


@pytest.fixture(scope="module")
def golden():
    # dual-toolchain resolution (tests/golden_tools.py): per-fingerprint
    # capture when one matches the running toolchain, else the legacy npz
    return golden_tools.load_golden(GOLDEN_PATH)


@pytest.mark.parametrize(
    "name,pkw,sources,fault_sched,ticks,seed",
    CONFIGS,
    ids=[c[0] for c in CONFIGS],
)
def test_trajectory_bit_identical(golden, name, pkw, sources, fault_sched, ticks, seed):
    params = delta.DeltaParams(**pkw)
    k = params.k
    traj = run_config(pkw, sources, fault_sched, ticks, seed)
    # fields added to the state after the LEGACY goldens were captured —
    # pinned by the invariant check below when the loaded capture predates
    # them; per-fingerprint captures carry every field (see
    # test_lifecycle_golden.py)
    post_capture_fields = {"ride_ok"}
    for field in delta.DeltaState._fields:
        if f"{name}/{field}" not in golden.files:
            assert field in post_capture_fields, f"stale golden: missing {field}"
            continue
        want = golden[f"{name}/{field}"]
        got = traj[field]
        if field in ("learned", "ride_ok"):
            want, got = _as_bool_plane(want, k), _as_bool_plane(got, k)
        assert got.shape == want.shape, (field, got.shape, want.shape)
        mism = np.flatnonzero((got != want).reshape(ticks, -1).any(axis=1))
        if mism.size:
            # classify toolchain drift vs real regression instead of a raw
            # array-mismatch assert (ROADMAP: 'Golden trajectories vs
            # toolchain drift')
            golden_tools.fail_golden(golden, name, field, int(mism[0]))
    # the carried ride_ok plane is derived state: its invariant pins it to
    # the golden-checked pcount at every tick
    max_p = delta.clamped_max_p(params)
    want_ride = traj["pcount"] < max_p
    got_ride = _as_bool_plane(traj["ride_ok"], k)
    assert (got_ride == want_ride).all(), f"{name}: ride_ok invariant broken"
