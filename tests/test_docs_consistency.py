"""Doc-vs-artifact consistency guard (VERDICT r4 item 7).

Round 4 shipped a PERF.md row quoting a superseded number for the sharded
1M step (9.1 s vs the committed artifact's 362.98 s) — the second
claim-vs-artifact mismatch class in two rounds.  This test makes the
quoted figures machine-checkable: any doc may annotate a quoted figure
with an invisible HTML comment

    <!--check: SIMBENCH_r05.json scenario(mc_churn_detection_n4096_x32).churn_cliff_at == 107-->

and this test resolves the path inside the committed artifact and
asserts equality.  Accessors:

- ``scenario(NAME)`` — the entry of the top-level ``scenarios`` list
  whose ``metric`` equals NAME (the SIMBENCH artifact shape);
- ``key`` / ``key.sub`` — dict field access;
- ``[i]`` — list index.

Values compare as floats when both sides parse as numbers, else as
case-sensitive strings (``true``/``false``/``null`` map to Python).

The test fails if an annotation's artifact is missing, its path does not
resolve, or the value differs — so editing an artifact without updating
the doc (or vice versa) turns the round-4 failure mode into a red test.
"""

from __future__ import annotations

import json
import os
import re

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DOCS = ["PERF.md", "README.md", "PARITY.md", "VERDICT_RESPONSE.md", "OBSERVABILITY.md"]

CHECK_RE = re.compile(r"<!--check:\s*(\S+)\s+(.+?)\s*(==|~=)\s*(.+?)\s*-->")


def _collect_checks():
    checks = []
    for doc in DOCS:
        path = os.path.join(REPO, doc)
        if not os.path.exists(path):
            continue
        text = open(path).read()
        for lineno, line in enumerate(text.splitlines(), 1):
            for m in CHECK_RE.finditer(line):
                checks.append((doc, lineno, m.group(1), m.group(2), m.group(3), m.group(4)))
    return checks


def _resolve(data, path: str):
    """Walk ``scenario(NAME)`` / ``key`` / ``[i]`` accessors."""
    # tokenize: scenario(...) | [int] | plain key, separated by dots
    tokens = re.findall(r"scenario\([^)]*\)|\[\d+\]|[^.\[\]]+", path)
    cur = data
    for tok in tokens:
        if tok.startswith("scenario("):
            name = tok[len("scenario("):-1]
            matches = [s for s in cur["scenarios"] if s.get("metric") == name]
            if not matches:
                raise KeyError(f"no scenario with metric={name!r}")
            cur = matches[0]
        elif tok.startswith("["):
            cur = cur[int(tok[1:-1])]
        else:
            cur = cur[tok]
    return cur


def _parse_value(text: str):
    mapped = {"true": True, "false": False, "null": None}
    if text in mapped:
        return mapped[text]
    try:
        return float(text)
    except ValueError:
        return text


CHECKS = _collect_checks()


def test_docs_carry_checks():
    """The mechanism is only a guard if the docs actually use it: the
    headline quoted figures must carry at least a handful of checks."""
    assert len(CHECKS) >= 5, (
        "fewer than 5 <!--check: ...--> annotations across "
        f"{DOCS}; the doc-vs-artifact guard is not wired up"
    )


@pytest.mark.parametrize(
    "doc,lineno,artifact,path,op,expect",
    CHECKS,
    ids=[f"{c[0]}:{c[1]}:{c[3]}" for c in CHECKS],
)
def test_doc_figure_matches_artifact(doc, lineno, artifact, path, op, expect):
    apath = os.path.join(REPO, artifact)
    assert os.path.exists(apath), f"{doc}:{lineno} cites missing artifact {artifact}"
    data = json.load(open(apath))
    actual = _resolve(data, path)
    expected = _parse_value(expect)
    if isinstance(expected, float) and isinstance(actual, (int, float)):
        if op == "~=":
            assert actual == pytest.approx(expected, rel=0.05), (
                f"{doc}:{lineno}: {artifact} {path} = {actual}, doc says ~{expected}"
            )
        else:
            assert float(actual) == expected, (
                f"{doc}:{lineno}: {artifact} {path} = {actual}, doc says {expected}"
            )
    else:
        assert actual == expected, (
            f"{doc}:{lineno}: {artifact} {path} = {actual!r}, doc says {expected!r}"
        )
