"""Quantitative lifecycle-vs-fullview engine agreement (VERDICT round-1
item 4).

The O(N·K) lifecycle engine documents four approximations against the exact
O(N²) fullview engine (``sim/lifecycle.py`` module docstring: per-rumor
suspicion timers, idle-on-unpingable-draw, re-seed-on-expiry, base-scoped
eviction).  These tests measure aggregate protocol behavior of both engines
at identical params and fault schedules across many seeds and assert the
approximations do not materially distort it.  Reference semantics under
test: ``swim/state_transitions.go:90-117`` (suspicion→faulty timing),
``swim/memberlist.go:337-354`` (refutation-by-reincarnation),
``swim/node.go:470-513`` (probe path).

Measured baseline for the chosen params (n=256, 6-seed pilot): detection
medians 22 (fullview) vs 24 (lifecycle) ticks; drop-induced refutation
counts 8.7 vs 10.5 mean; recovery 100% both.  Tolerances below are ~3x the
observed gaps, so they catch a *material* distortion (e.g. a broken timer
path doubling detection latency), not seed noise.
"""

from __future__ import annotations

import numpy as np
import pytest

from tests.engine_agreement import (
    detection_latency,
    quiescence_run,
    refutation_run,
)

N = 256
SEEDS = 20


@pytest.mark.slow
def test_detection_latency_distributions_agree():
    """Crash 3 nodes; both engines must detect in every seed, with medians
    within 8 ticks and means within 1.5x of each other."""
    rng = np.random.default_rng(7)
    victim_sets = [
        sorted(rng.choice(N, size=3, replace=False).tolist()) for _ in range(SEEDS)
    ]
    max_ticks = 400
    fv = np.array(
        [detection_latency("fullview", N, 100 + s, victim_sets[s]) for s in range(SEEDS)],
        float,
    )
    lc = np.array(
        [detection_latency("lifecycle", N, 100 + s, victim_sets[s]) for s in range(SEEDS)],
        float,
    )
    assert (fv < max_ticks).all(), f"fullview failed to detect: {fv}"
    assert (lc < max_ticks).all(), f"lifecycle failed to detect: {lc}"
    assert abs(np.median(fv) - np.median(lc)) <= 8, (np.median(fv), np.median(lc))
    ratio = lc.mean() / fv.mean()
    assert 1 / 1.5 <= ratio <= 1.5, (fv.mean(), lc.mean())


@pytest.mark.slow
def test_refutation_counts_and_recovery_agree():
    """10% packet loss for 60 ticks breeds false suspicions; once the loss
    stops, every seed must refute its way back to an all-alive converged
    view in both engines, with refutation counts of the same magnitude."""
    fv = [refutation_run("fullview", N, 200 + s) for s in range(SEEDS)]
    lc = [refutation_run("lifecycle", N, 200 + s) for s in range(SEEDS)]
    assert all(r[1] for r in fv), f"fullview failed to recover: {fv}"
    assert all(r[1] for r in lc), f"lifecycle failed to recover: {lc}"
    fv_counts = np.array([r[0] for r in fv], float)
    lc_counts = np.array([r[0] for r in lc], float)
    # loss at this rate must actually cause refutations (else the scenario
    # is vacuous), and the engines must agree within 3x on how many
    assert fv_counts.mean() > 0 and lc_counts.mean() > 0
    ratio = lc_counts.mean() / fv_counts.mean()
    assert 1 / 3 <= ratio <= 3, (fv_counts.mean(), lc_counts.mean())


def test_steady_state_quiescence_agrees():
    """No faults: neither engine may generate any protocol traffic state —
    the approximations must not manufacture rumors out of nothing."""
    for seed in (1, 2, 3):
        assert quiescence_run("fullview", N, seed)
        assert quiescence_run("lifecycle", N, seed)
