"""Quantitative lifecycle-vs-fullview engine agreement (VERDICT round-1
item 4).

The O(N·K) lifecycle engine documents four approximations against the exact
O(N²) fullview engine (``sim/lifecycle.py`` module docstring: per-rumor
suspicion timers, idle-on-unpingable-draw, re-seed-on-expiry, base-scoped
eviction).  These tests measure aggregate protocol behavior of both engines
at identical params and fault schedules across many seeds and assert the
approximations do not materially distort it.  Reference semantics under
test: ``swim/state_transitions.go:90-117`` (suspicion→faulty timing),
``swim/memberlist.go:337-354`` (refutation-by-reincarnation),
``swim/node.go:470-513`` (probe path).

Measured baseline for the chosen params (n=256, 50 seeds, round 3):
detection medians 22 (fullview) vs 24 (lifecycle), p90 24 vs 24, mean
ratio 1.04; drop-induced refutation counts 9.5 vs 9.1 mean; recovery 100%
both (lifecycle settles faster post-drop: median 8 vs 40 ticks — the
aggregate representation folds refutations in one pass).  Tolerances
below sit just above those measured gaps — p50/p90 within 2 ticks, mean
ratio within 1.15x — tight enough that a ~15% systematic distortion from
any of the four documented lifecycle approximations
(``sim/lifecycle.py`` module docstring) fails the suite.  The runs are
fully seeded, so the assertions are deterministic, not flaky.
"""

from __future__ import annotations

import numpy as np
import pytest

from tests.engine_agreement import (
    detection_latency,
    partition_run,
    quiescence_run,
    refutation_run,
)

N = 256
SEEDS = 50
PARTITION_SEEDS = 8


@pytest.mark.slow
def test_detection_latency_distributions_agree():
    """Crash 3 nodes; both engines must detect in every seed, with p50 and
    p90 within 2 ticks and means within 1.15x of each other."""
    rng = np.random.default_rng(7)
    victim_sets = [
        sorted(rng.choice(N, size=3, replace=False).tolist()) for _ in range(SEEDS)
    ]
    max_ticks = 400
    fv = np.array(
        [detection_latency("fullview", N, 100 + s, victim_sets[s]) for s in range(SEEDS)],
        float,
    )
    lc = np.array(
        [detection_latency("lifecycle", N, 100 + s, victim_sets[s]) for s in range(SEEDS)],
        float,
    )
    assert (fv < max_ticks).all(), f"fullview failed to detect: {fv}"
    assert (lc < max_ticks).all(), f"lifecycle failed to detect: {lc}"
    assert abs(np.median(fv) - np.median(lc)) <= 2, (np.median(fv), np.median(lc))
    assert abs(np.percentile(fv, 90) - np.percentile(lc, 90)) <= 2, (
        np.percentile(fv, 90),
        np.percentile(lc, 90),
    )
    ratio = lc.mean() / fv.mean()
    assert 1 / 1.15 <= ratio <= 1.15, (fv.mean(), lc.mean())


@pytest.mark.slow
def test_refutation_counts_and_recovery_agree():
    """10% packet loss for 60 ticks breeds false suspicions; once the loss
    stops, every seed must refute its way back to an all-alive converged
    view in both engines, with refutation counts of the same magnitude."""
    fv = [refutation_run("fullview", N, 200 + s) for s in range(SEEDS)]
    lc = [refutation_run("lifecycle", N, 200 + s) for s in range(SEEDS)]
    assert all(r[1] for r in fv), f"fullview failed to recover: {fv}"
    assert all(r[1] for r in lc), f"lifecycle failed to recover: {lc}"
    fv_counts = np.array([r[0] for r in fv], float)
    lc_counts = np.array([r[0] for r in lc], float)
    # loss at this rate must actually cause refutations (else the scenario
    # is vacuous), and the engines must agree within 3x on how many
    assert fv_counts.mean() > 0 and lc_counts.mean() > 0
    ratio = lc_counts.mean() / fv_counts.mean()
    assert 1 / 3 <= ratio <= 3, (fv_counts.mean(), lc_counts.mean())


@pytest.mark.slow
def test_asymmetric_partition_recovery_agrees():
    """30/70 hard partition, healed while cross-suspicions are in flight:
    both engines must breed cross-partition suspicion mass of the same
    magnitude during the split and, once healed, return every seed to an
    all-alive converged view (reference semantics:
    ``swim/node.go:494-510`` indirect-probe suspicion across a split +
    ``memberlist.go:337-354`` refutation)."""
    fv = [partition_run("fullview", N, 300 + s) for s in range(PARTITION_SEEDS)]
    lc = [partition_run("lifecycle", N, 300 + s) for s in range(PARTITION_SEEDS)]
    assert all(r[1] for r in fv), f"fullview failed to recover: {fv}"
    assert all(r[1] for r in lc), f"lifecycle failed to recover: {lc}"
    fv_cross = np.array([r[0] for r in fv], float)
    lc_cross = np.array([r[0] for r in lc], float)
    # the split must actually cause cross-partition suspicion in both
    # engines, at the same magnitude
    assert fv_cross.mean() > 0 and lc_cross.mean() > 0, (fv_cross, lc_cross)
    ratio = lc_cross.mean() / fv_cross.mean()
    assert 1 / 3 <= ratio <= 3, (fv_cross.mean(), lc_cross.mean())


def test_steady_state_quiescence_agrees():
    """No faults: neither engine may generate any protocol traffic state —
    the approximations must not manufacture rumors out of nothing."""
    for seed in (1, 2, 3):
        assert quiescence_run("fullview", N, seed)
        assert quiescence_run("lifecycle", N, seed)
