"""Every example must actually run — they are the user-facing 'switch from
the reference' demos (PARITY §2.7), and nothing else executes them, so an
API drift would rot them silently (the round-4 Monte-Carlo churn addition
touched exactly such a path).  Each runs in its own subprocess (they pin
their own CPU backend before jax init) and must exit 0 with its closing
line intact."""

import os
import subprocess
import sys

import pytest

EXAMPLES = {
    "ping_json.py": "ok=True",
    "keyed_service.py": "ring owner",
    "montecarlo_study.py": "churn",
    "failure_study.py": "bit-exact: True",
}

_REPO = os.path.join(os.path.dirname(__file__), "..")


@pytest.mark.slow
@pytest.mark.parametrize("name,expect", sorted(EXAMPLES.items()))
def test_example_runs(name, expect):
    from ringpop_tpu.util.accel import compile_cache_dir

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    # run the example as a USER would: without the suite's virtual-8-device
    # XLA_FLAGS mutation (tests/conftest.py sets it at import), and with
    # jax's native cache env var pointed at the shared fingerprinted dir so
    # CI runs don't pay full sim-engine recompiles per example
    env.pop("XLA_FLAGS", None)
    env["JAX_COMPILATION_CACHE_DIR"] = compile_cache_dir(
        os.path.join(_REPO, ".jax_cache")
    )
    r = subprocess.run(
        [sys.executable, os.path.join(_REPO, "examples", name)],
        capture_output=True,
        text=True,
        timeout=600,
        cwd=_REPO,
        env=env,
    )
    assert r.returncode == 0, (r.stdout[-500:], r.stderr[-1000:])
    assert expect in r.stdout, r.stdout[-500:]
