"""r16 async-fabric suite: ``exchange_async`` completions must be
result- and accounting-identical to the synchronous rounds they replace
(persistent per-peer sender threads + the tagged receive demux), the
XOR-delta stream history must stay exact with several rounds in flight
(the double-buffering contract the overlapped engine leans on), a
multi-peer outage must aggregate EVERY failed leg into one raise, and
the swing (distance-halving) schedule must route window pieces to their
destinations in <= log2(P) power-of-two hops with byte-identical
assembly vs the cyclic plan.
"""

import threading
import time

import numpy as np
import pytest

from ringpop_tpu.parallel.fabric import (
    Fabric,
    FabricError,
    FabricPeerLost,
    FabricTimeout,
    LocalKV,
    plan_window,
    plan_window_swing,
    window_pieces,
)


def _run_ranks(nprocs, body, ns, timeout_ms=120_000, codec=True, join_s=60):
    kv = LocalKV()
    out, errs = [None] * nprocs, [None] * nprocs

    def run(rank):
        try:
            with Fabric(rank, nprocs, kv, namespace=ns, timeout_ms=timeout_ms,
                        codec=codec) as fab:
                out[rank] = body(fab, rank)
        except BaseException as e:
            errs[rank] = e

    ts = [threading.Thread(target=run, args=(r,), daemon=True) for r in range(nprocs)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(join_s)
    assert not any(t.is_alive() for t in ts), "a rank hung past the join budget"
    return out, errs


# -- async == sync ------------------------------------------------------------


def _round_payloads(rank, tick, rng_seed=13):
    rng = np.random.default_rng(rng_seed + 31 * rank + tick)
    sparse = np.zeros((128, 4), np.uint32)
    sparse[rng.choice(128, 9, replace=False)] = rng.integers(
        1, 2**32, (9, 4), dtype=np.uint32
    )
    dense = rng.integers(1, 2**32, (32, 4), dtype=np.uint32)
    return [sparse, dense]


def test_async_rounds_equal_sync_rounds_including_accounting():
    """Two legs per tick for several ticks, once through blocking
    ``exchange`` and once with BOTH legs' handles held in flight before
    either is waited: identical arrays out, identical wire/raw byte
    totals and codec mix (packing happens at enqueue either way)."""

    def sync_body(fab, rank):
        peer = 1 - rank
        seen = []
        for tick in range(3):
            a = fab.exchange(tick * 16, {peer: _round_payloads(rank, tick)}, [peer])
            b = fab.exchange(tick * 16 + 1, {peer: _round_payloads(rank, tick + 100)}, [peer])
            seen.append((a[peer], b[peer]))
        return seen, fab.wire_stats()

    def async_body(fab, rank):
        peer = 1 - rank
        seen = []
        for tick in range(3):
            h1 = fab.exchange_async(tick * 16, {peer: _round_payloads(rank, tick)}, [peer])
            h2 = fab.exchange_async(
                tick * 16 + 1, {peer: _round_payloads(rank, tick + 100)}, [peer]
            )
            # both rounds in flight; join receives only — the drain is
            # the sender threads' business
            a = h1.wait(join_sends=False)
            b = h2.wait(join_sends=False)
            seen.append((a[peer], b[peer]))
        return seen, fab.wire_stats()

    out_s, errs_s = _run_ranks(2, sync_body, "asyncs")
    out_a, errs_a = _run_ranks(2, async_body, "asynca")
    assert errs_s == [None, None] and errs_a == [None, None], (errs_s, errs_a)
    for rank in range(2):
        seen_s, ws_s = out_s[rank]
        seen_a, ws_a = out_a[rank]
        for (a_s, b_s), (a_a, b_a) in zip(seen_s, seen_a):
            for x, y in zip(a_s + b_s, a_a + b_a):
                assert x.tobytes() == y.tobytes()
        assert ws_s == ws_a, "async round accounting diverged from sync"


def test_inflight_stream_xor_history_stays_exact():
    """Several STREAMED rounds enqueued before any is waited: the
    XOR-delta payload history advances in enqueue order on the sender
    and FIFO decode order on the receiver, so every round decodes exact
    — and the wire total matches the fully synchronous run (same
    encodings chosen)."""
    base = np.zeros((64, 4), np.uint32)
    base[5] = 7

    def payload(tick):
        a = base.copy()
        a[0, 0] = tick
        return a

    def async_body(fab, rank):
        peer = 1 - rank
        handles = [
            fab.exchange_async(t, {peer: [payload(t)]}, [peer], stream="s")
            for t in range(4)
        ]
        got = [h.wait(join_sends=False) for h in handles]
        return [g[peer][0] for g in got], fab.wire_stats()

    def sync_body(fab, rank):
        peer = 1 - rank
        got = [
            fab.exchange(t, {peer: [payload(t)]}, [peer], stream="s")
            for t in range(4)
        ]
        return [g[peer][0] for g in got], fab.wire_stats()

    out_a, errs_a = _run_ranks(2, async_body, "xora")
    out_s, errs_s = _run_ranks(2, sync_body, "xors")
    assert errs_a == [None, None] and errs_s == [None, None], (errs_a, errs_s)
    for rank in range(2):
        arrs, ws_a = out_a[rank]
        refs, ws_s = out_s[rank]
        for t, (a, r) in enumerate(zip(arrs, refs)):
            assert a.tobytes() == payload(t).tobytes()
            assert a.tobytes() == r.tobytes()
        assert ws_a == ws_s
        # the stream actually engaged the XOR codec past the first round
        assert ws_a["codec_counts"].get("xor", 0) >= 1, ws_a["codec_counts"]


# -- failure modes under in-flight completions --------------------------------


def test_two_dead_peers_aggregate_into_one_raise():
    """A round failing on SEVERAL peers must surface every failure:
    before r16 only ``errs[0]`` escaped and a multi-peer outage read as
    a single-peer one.  Ranks 1 and 2 die after bring-up; rank 3 stays
    honest; rank 0's exchange must raise with BOTH dead peers attached
    (``peer_errors`` + the ``__context__`` chain)."""

    def body(fab, rank):
        if rank in (1, 2):
            fab.close()
            return "died"
        if rank == 3:
            try:
                fab.exchange(
                    0, {0: [np.arange(4, dtype=np.uint32)]}, [0]
                )
            except FabricError:
                pass  # rank 0 may abort before sending back
            return "peer3"
        time.sleep(0.3)  # let 1 and 2 actually die first
        peers = [1, 2, 3]
        fab.exchange(
            0, {p: [np.arange(4, dtype=np.uint32)] for p in peers}, peers
        )
        return "unreachable"

    out, errs = _run_ranks(4, body, "twodead", timeout_ms=30_000)
    assert out[1] == "died" and out[2] == "died"
    e = errs[0]
    assert isinstance(e, FabricError), e
    attached = getattr(e, "peer_errors", (e,))
    assert len(attached) >= 2, f"second dead peer dropped: {attached}"
    texts = [str(x) for x in attached]
    assert any("peer 1" in t for t in texts), texts
    assert any("peer 2" in t for t in texts), texts
    # the chain renders in ONE traceback: context links the rest
    assert e.__context__ is not None


def test_kill_one_rank_fails_inflight_completion_promptly():
    """A peer dying while a completion handle is already in flight must
    fail that handle's wait() with a typed FabricPeerLost promptly — not
    at timeout_ms, and not silently at the next round."""

    def body(fab, rank):
        if rank == 1:
            got = fab.exchange(0, {0: [np.arange(3, dtype=np.uint32)]}, [0])
            assert got[0][0].shape == (3,)
            fab.close()  # die with rank 0's tick-1 expectation in flight
            return "died"
        fab.exchange(0, {1: [np.arange(3, dtype=np.uint32)]}, [1])
        h = fab.exchange_async(1, {1: [np.arange(3, dtype=np.uint32)]}, [1])
        t0 = time.monotonic()
        with pytest.raises(FabricPeerLost, match="peer 1"):
            h.wait()
        return time.monotonic() - t0

    out, errs = _run_ranks(2, body, "killinflight", timeout_ms=30_000)
    assert errs == [None, None], errs
    assert out[1] == "died"
    assert out[0] < 15, f"peer-lost took {out[0]}s — that is a timeout, not EOF"


def test_stalled_peer_times_out_inflight_completion():
    """A live-but-silent peer fails an in-flight completion with
    FabricTimeout at ~timeout_ms (the demux thread's socket timeout)."""

    def body(fab, rank):
        if rank == 1:
            time.sleep(2.0)  # wedged: never sends
            return "stalled"
        h = fab.exchange_async(7, {}, [1])
        with pytest.raises(FabricTimeout, match="peer 1"):
            h.wait()
        return "timed-out"

    out, errs = _run_ranks(2, body, "stallinflight", timeout_ms=600)
    assert errs == [None, None], errs
    assert out == ["timed-out", "stalled"]


def test_unjoined_send_error_is_sticky_and_surfaces_at_next_enqueue():
    """Overlap mode never joins sends — a drain failure must not vanish:
    the sender thread's sticky error fails the NEXT exchange_async on
    that fabric."""

    def body(fab, rank):
        if rank == 1:
            got = fab.exchange(0, {0: [np.zeros(2, np.uint32)]}, [0])
            fab.close()
            return "died"
        fab.exchange(0, {1: [np.zeros(2, np.uint32)]}, [1])
        # big payload so the drain outlives the peer's close; never joined
        big = np.arange(2_000_000, dtype=np.uint32)
        fab.exchange_async(1, {1: [big]}, [])
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            time.sleep(0.05)
            try:
                fab.exchange_async(2, {1: [np.zeros(2, np.uint32)]}, [])
            except FabricError:
                return "sticky-surfaced"
        return "never-surfaced"

    out, errs = _run_ranks(2, body, "sticky", timeout_ms=30_000)
    assert errs == [None, None], errs
    assert out[0] == "sticky-surfaced"


def test_close_fails_every_queued_expectation_promptly():
    """close() with SEVERAL receive expectations still queued on one
    peer must fail every queued future promptly — the drain helper has
    to recognize the typed _RecvJob (itself a tuple) and not die on it,
    which would leave later waiters blocking into a misleading
    timeout."""

    def body(fab, rank):
        if rank == 1:
            time.sleep(0.4)  # never sends; peer 0 closes on its own
            return "idle"
        h1 = fab.exchange_async(0, {}, [1])
        h2 = fab.exchange_async(1, {}, [1])
        fab.close()
        t0 = time.monotonic()
        for h in (h1, h2):
            with pytest.raises(FabricError):
                h.wait()
        return time.monotonic() - t0

    out, errs = _run_ranks(2, body, "closeq", timeout_ms=10_000)
    assert errs == [None, None], errs
    assert out[1] == "idle"
    assert out[0] < 5, f"queued expectation hung {out[0]}s past the close"


def test_closed_fabric_refuses_rounds():
    fab = Fabric(0, 1, LocalKV())
    fab.close()
    with pytest.raises(FabricError, match="closed"):
        fab.exchange_async(0, {}, [])


# -- the swing schedule -------------------------------------------------------


def _simulate_swing(plane, rel_start, nprocs):
    """Pure-host replay of the swing manifests: every rank's store
    stepped through the rounds with in-memory delivery — an independent
    executor for the plan, so the engine's device path is not the only
    interpretation of the schedule."""
    n = plane.shape[0]
    b = n // nprocs
    rounds = plan_window_swing(rel_start % n, n, nprocs)
    stores = [dict() for _ in range(nprocs)]
    hops: dict[tuple, int] = {}
    for j, manifest in enumerate(rounds):
        moved = []
        for holder, entries in manifest.items():
            dst_rank = holder ^ (1 << j)
            for entry in entries:
                d, owner, glo, glen, woff = entry
                if owner == holder:
                    payload = plane[glo : glo + glen]
                else:
                    payload = stores[holder].pop(entry)
                moved.append((dst_rank, entry, payload))
                hops[entry] = hops.get(entry, 0) + 1
        for dst_rank, entry, payload in moved:
            stores[dst_rank][entry] = payload
    # assemble every rank's window and check hop bounds
    out = []
    log_p = nprocs.bit_length() - 1
    for entry, k in hops.items():
        assert k <= log_p, f"{entry} took {k} hops > log2(P)={log_p}"
        assert k == bin(entry[1] ^ entry[0]).count("1")
    for r in range(nprocs):
        lo = r * b
        my_plan = plan_window((lo + rel_start) % n, b, n, nprocs)
        parts = []
        for owner, glo, glen, woff in my_plan:
            if owner == r:
                parts.append(plane[glo : glo + glen])
            else:
                parts.append(stores[r].pop((r, owner, glo, glen, woff)))
        assert not stores[r], f"rank {r} left undelivered pieces: {stores[r]}"
        out.append(np.concatenate(parts) if len(parts) > 1 else parts[0])
    return out


def test_swing_assembly_byte_identical_to_cyclic_property_sweep():
    """Random (n, P, shift, K): the swing-relayed window of every rank
    equals both the cyclic-plan assembly and the direct cyclic-take
    oracle, byte for byte."""
    rng = np.random.default_rng(42)
    for trial in range(40):
        nprocs = int(rng.choice([2, 4, 8, 16]))
        n = nprocs * int(rng.integers(1, 9))
        k = int(rng.integers(1, 5))
        shift = int(rng.integers(-2 * n, 2 * n))
        plane = rng.integers(0, 2**32, (n, k), dtype=np.uint32)
        b = n // nprocs
        windows = _simulate_swing(plane, shift, nprocs)
        for r in range(nprocs):
            start = (r * b + shift) % n
            oracle = np.take(
                plane, (start + np.arange(b)) % n, axis=0
            )
            assert windows[r].tobytes() == oracle.tobytes(), (
                trial, n, nprocs, shift, r
            )


def test_swing_plan_refuses_non_power_of_two():
    with pytest.raises(ValueError, match="power-of-two"):
        plan_window_swing(1, 12, 3)


def test_swing_allgather_matches_cyclic_bitwise():
    """allgather(schedule='swing') returns the same per-rank list as the
    cyclic full mesh — including with an XOR stream attached — so any
    bitwise reduce over it is schedule-invariant."""
    arrs = {
        r: np.arange(8, dtype=np.uint32) * (r + 1) for r in range(4)
    }

    def body_for(schedule):
        def body(fab, rank):
            got = []
            for tick in range(3):
                a = arrs[rank] + tick
                got.append(
                    fab.allgather(tick * 16, a, stream="reduce", schedule=schedule)
                )
            return got
        return body

    out_c, errs_c = _run_ranks(4, body_for("cyclic"), "agc")
    out_s, errs_s = _run_ranks(4, body_for("swing"), "ags")
    assert errs_c == [None] * 4 and errs_s == [None] * 4, (errs_c, errs_s)
    for rank in range(4):
        for tick in range(3):
            ref = [arrs[r] + tick for r in range(4)]
            for a, b, c in zip(out_c[rank][tick], out_s[rank][tick], ref):
                assert a.tobytes() == b.tobytes() == c.tobytes()


def test_swing_allgather_refuses_non_power_of_two():
    def body(fab, rank):
        with pytest.raises(ValueError, match="power-of-two"):
            fab.allgather(0, np.zeros(2, np.uint32), schedule="swing")
        return "refused"

    out, errs = _run_ranks(3, body, "agrefuse")
    assert errs == [None] * 3, errs
    assert out == ["refused"] * 3


# -- plan_window hardening (r16 satellite) ------------------------------------


def test_plan_window_refuses_non_divisible_n():
    """Pre-r16 this silently planned over truncated b = n // nprocs
    blocks, leaving the ring's tail rows owned by nobody."""
    with pytest.raises(ValueError, match="divide"):
        plan_window(0, 25, 100, 3)
    with pytest.raises(ValueError, match="divide"):
        plan_window(7, 5, 17, 4)


def test_window_edges_zero_length_full_ring_large_shift():
    # zero-length window: empty pieces, empty plan (previously an
    # internal assert tripped on a degenerate intersect)
    assert window_pieces(5, 0, 64) == []
    assert plan_window(5, 0, 64, 4) == []
    # full-ring window (P=1 uses block == n)
    assert window_pieces(0, 64, 64) == [(0, 64)]
    assert window_pieces(10, 64, 64) == [(10, 54), (0, 10)]
    plan = plan_window(10, 64, 64, 1)
    assert sum(glen for _, _, glen, _ in plan) == 64
    # shift >= n and negative shifts reduce mod n
    assert window_pieces(100, 8, 64) == window_pieces(36, 8, 64)
    assert plan_window(-28, 8, 64, 4) == plan_window(36, 8, 64, 4)
    # over-long window is a loud contract violation, not a double-cover
    with pytest.raises(ValueError, match="outside"):
        window_pieces(0, 65, 64)


def test_plan_window_non_power_of_two_process_count():
    """The cyclic plan stays correct at P=3 (swing is the one that
    requires a power of two): full coverage, right owners."""
    n, nprocs = 96, 3
    b = n // nprocs
    for start in (0, 1, 31, 32, 63, 95):
        plan = plan_window(start, b, n, nprocs)
        covered = sorted(
            (woff + i, (glo + i) % n)
            for _, glo, glen, woff in plan
            for i in range(glen)
        )
        assert [c[0] for c in covered] == list(range(b))
        for owner, glo, glen, _ in plan:
            assert owner == glo // b, "piece assigned off its owner block"


# -- wire_stats under concurrent senders (r20 obs satellite) ------------------


def test_wire_stats_race_free_and_monotone_under_concurrent_senders():
    """Both ranks enqueue rounds as fast as they can (packing in the
    caller thread, draining on the persistent sender threads — three
    threads touching the counters per rank) while a monitor thread
    polls ``wire_stats()``: every snapshot must be internally
    consistent and monotone non-decreasing, and the final totals must
    balance exactly (rank 0's wire/raw sent == rank 1's received and
    vice versa — a lost or double-counted update cannot balance)."""
    rounds = 40
    monitor_stop = threading.Event()
    snaps: dict[int, list] = {0: [], 1: []}

    def body(fab, rank):
        peer = 1 - rank

        def monitor():
            while not monitor_stop.is_set():
                snaps[rank].append(fab.wire_stats())
                time.sleep(0.001)

        mt = threading.Thread(target=monitor, daemon=True)
        mt.start()
        handles = []
        for tick in range(rounds):
            handles.append(
                fab.exchange_async(
                    tick * 16, {peer: _round_payloads(rank, tick)}, [peer]
                )
            )
            if len(handles) >= 4:  # keep several rounds in flight
                handles.pop(0).wait(join_sends=False)
        for h in handles:
            h.wait()  # join everything (sends too) before reading finals
        final = fab.wire_stats()
        mt.join(timeout=5)
        return final

    out, errs = _run_ranks(2, body, "wirestats-conc")
    monitor_stop.set()
    assert errs == [None, None], errs
    for rank in range(2):
        series = snaps[rank] + [out[rank]]
        for prev, cur in zip(series, series[1:]):
            for key in ("bytes_sent", "bytes_recv", "raw_bytes_sent",
                        "raw_bytes_recv"):
                assert cur[key] >= prev[key], (
                    f"rank {rank}: {key} went backwards: {prev} -> {cur}"
                )
        # raw is never below wire (the codec only ever shrinks)
        assert out[rank]["raw_bytes_sent"] >= out[rank]["bytes_sent"]
    # exact cross-rank balance: totals are race-free or they don't add up
    assert out[0]["bytes_sent"] == out[1]["bytes_recv"]
    assert out[1]["bytes_sent"] == out[0]["bytes_recv"]
    assert out[0]["raw_bytes_sent"] == out[1]["raw_bytes_recv"]
    assert out[1]["raw_bytes_sent"] == out[0]["raw_bytes_recv"]
    # per-codec sent counts: one entry per array that crossed, so the
    # two ranks' totals agree (same deterministic payload schedule)
    assert sum(out[0]["codec_counts"].values()) == rounds * 2
    assert out[0]["codec_counts"] == out[1]["codec_counts"]


def test_shm_lane_closed_exactly_once_under_nak_fail_race():
    """Pins the RPH304 fix: ``RpcLink._shm`` is installed and detached
    only under ``_lock``, so a peer NAK racing ``_fail`` swaps the lane
    out atomically — exactly one path observes it and closes it.  A
    double close would tear down a recycled shm fd; a missed close leaks
    the segment."""
    from ringpop_tpu.parallel.fabric import RpcLink

    class Lane:
        def __init__(self):
            self.closes = 0
            self._mx = threading.Lock()

        def close(self):
            with self._mx:
                self.closes += 1

    class Sock:
        def shutdown(self, how):
            pass

        def close(self):
            pass

    class Ep:
        def _unregister(self, link):
            pass

    for trial in range(50):
        link = RpcLink.__new__(RpcLink)
        link._lock = threading.Lock()
        link.err = None
        link._pending = {}
        link.ep = Ep()
        link.sock = Sock()
        link.peer = None
        lane = Lane()
        link._shm = lane
        start = threading.Barrier(2)

        def nak():
            start.wait()
            link._handle_ctl(b'{"op":"nak"}')

        def fail():
            start.wait()
            link._fail(FabricError("race trial"))

        ts = [threading.Thread(target=nak), threading.Thread(target=fail)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=10)
        assert lane.closes == 1, f"trial {trial}: closed {lane.closes}x"
        assert link._shm is None
