"""r15 wire-codec suite: the fabric's self-describing per-array codec
(RAW / ROWS / RUNS / XOR-delta) must round-trip EXACTLY for every dtype
and adversarial shape the fabric ships, pick only strictly-smaller
encodings (the measured raw fallback), and carry an epoch word that turns
a missed XOR reset (snapshot restore / peer-count change) into a loud
error.  Plus the r15 fabric-robustness fix: a dead or silent peer
surfaces as a typed fabric error with rank/peer context — never a hang,
and never mistakable for a tag desync.
"""

import threading
import time

import numpy as np
import pytest

from ringpop_tpu.parallel.fabric import (
    CODEC_RAW,
    CODEC_ROWS,
    CODEC_RUNS,
    CODEC_XOR,
    Encoded,
    Fabric,
    FabricError,
    FabricPeerLost,
    FabricTimeout,
    LocalKV,
    decode_array,
    encode_array,
    encode_rows,
    rows_wire_size,
)

# every dtype the fabric ships today (uint32 planes, int8 pcount, bool
# masks, int64 coverage counts, float32 rates) plus paranoia extras
DTYPES = [np.uint32, np.int8, np.uint8, np.int32, np.int64, np.float32, bool]


def _roundtrip(a, prev=None, epoch=0):
    e = encode_array(a, prev=prev, epoch=epoch)
    d = decode_array(e.codec, e.dtype, e.shape, e.payload, prev=prev, epoch=epoch)
    ref = np.ascontiguousarray(a)
    assert d.dtype == ref.dtype and d.shape == ref.shape
    # BIT equality, unconditionally: value-equality would wave through a
    # canonicalizing codec (float -0.0 → +0.0) that breaks digest parity
    assert d.tobytes() == ref.tobytes(), "round trip not bit-exact"
    assert len(e.payload) <= e.raw_nbytes, "codec grew the payload"
    return e


@pytest.mark.parametrize("dtype", DTYPES)
def test_roundtrip_adversarial_planes_every_dtype(dtype):
    rng = np.random.default_rng(7)
    planes = [
        np.zeros((33, 5), dtype),  # all-zero
        np.ones((33, 5), dtype),  # all-ones
        np.zeros((1, 4), dtype),  # single row
        np.zeros((0, 4), dtype),  # empty
        rng.integers(0, 2, (64, 3)).astype(dtype),  # random sparse
        np.eye(17).astype(dtype),  # diagonal
    ]
    one_hot = np.zeros((257, 3), dtype)
    one_hot[128] = np.ones(3, dtype)
    planes.append(one_hot)
    if np.dtype(dtype) == np.float32:
        # bit-distinct-but-value-zero and NaN rows: the ROWS mask must
        # work on the byte view or these rows canonicalize
        tricky = np.zeros((64, 3), np.float32)
        tricky[7] = -0.0
        tricky[9] = np.nan
        planes.append(tricky)
    for a in planes:
        _roundtrip(a)


def test_roundtrip_random_property_sweep():
    rng = np.random.default_rng(11)
    for trial in range(120):
        dtype = DTYPES[trial % len(DTYPES)]
        rows = int(rng.integers(1, 80))
        cols = int(rng.integers(1, 9))
        density = rng.choice([0.0, 0.02, 0.3, 1.0])
        a = (rng.random((rows, cols)) < density) * rng.integers(
            1, 100, (rows, cols)
        )
        a = a.astype(dtype)
        prev = None
        if trial % 3 == 0:
            flips = (rng.random((rows, cols)) < 0.05).astype(dtype)
            prev = np.ascontiguousarray((a + flips).astype(dtype)).tobytes()
        _roundtrip(a, prev=prev, epoch=trial)


def test_measured_fallbacks_pick_the_smallest_encoding():
    rng = np.random.default_rng(3)
    # dense random: nothing pays -> RAW
    dense = rng.integers(1, 2**32, (64, 4), dtype=np.uint32)
    assert encode_array(dense).codec == CODEC_RAW
    # scattered dense-random rows: ROWS beats RUNS and raw
    plane = np.zeros((1000, 4), np.uint32)
    plane[rng.choice(1000, 100, replace=False)] = rng.integers(
        1, 2**32, (100, 4), dtype=np.uint32
    )
    assert encode_array(plane).codec == CODEC_ROWS
    # dense-but-patchy columns: every row nonzero, zero-word runs inside
    patchy = rng.integers(1, 2**32, (64, 8), dtype=np.uint32)
    patchy[:, 2:7] = 0
    assert encode_array(patchy).codec == CODEC_RUNS
    # one nonzero row: RUNS undercuts even ROWS (no per-row bitmap cost)
    lone = np.zeros((1000, 4), np.uint32)
    lone[7] = 9
    e = encode_array(lone)
    assert e.codec == CODEC_RUNS and len(e.payload) < 64


def test_encode_rows_is_wire_identical_to_host_encoder():
    """The device-sourced pre-encoding (mask + compacted rows) must
    produce byte-identical frames to the host-side chooser's ROWS path."""
    rng = np.random.default_rng(5)
    plane = np.zeros((200, 2), np.uint32)
    plane[rng.choice(200, 40, replace=False)] = rng.integers(
        1, 2**32, (40, 2), dtype=np.uint32
    )
    mask = (plane != 0).any(axis=1)
    pre = encode_rows(mask, plane[mask], plane.shape, plane.dtype)
    host = encode_array(plane)
    assert host.codec == CODEC_ROWS
    assert pre.payload == host.payload and pre.codec == host.codec
    assert rows_wire_size(200, int(mask.sum()), 8) == len(pre.payload)


def test_xor_epoch_desync_is_loud():
    rng = np.random.default_rng(9)
    a0 = rng.integers(1, 2**32, (64, 8), dtype=np.uint32)
    a1 = a0.copy()
    a1[3, 2] ^= 12345
    e = encode_array(a1, prev=a0.tobytes(), epoch=4)
    assert e.codec == CODEC_XOR
    d = decode_array(e.codec, e.dtype, e.shape, e.payload, prev=a0.tobytes(), epoch=4)
    assert np.array_equal(d, a1)
    with pytest.raises(FabricError, match="epoch desync"):
        decode_array(e.codec, e.dtype, e.shape, e.payload, prev=a0.tobytes(), epoch=5)
    with pytest.raises(FabricError, match="epoch desync"):
        decode_array(e.codec, e.dtype, e.shape, e.payload, prev=None, epoch=4)


# -- the codec through a live fabric ------------------------------------------


def _run_ranks(nprocs, body, ns, timeout_ms=120_000, codec=True, join_s=60):
    """Spin nprocs threaded ranks over one LocalKV; each runs
    ``body(fabric, rank)``; per-rank return values / exceptions out."""
    kv = LocalKV()
    out, errs = [None] * nprocs, [None] * nprocs

    def run(rank):
        try:
            with Fabric(rank, nprocs, kv, namespace=ns, timeout_ms=timeout_ms,
                        codec=codec) as fab:
                out[rank] = body(fab, rank)
        except BaseException as e:
            errs[rank] = e

    ts = [threading.Thread(target=run, args=(r,), daemon=True) for r in range(nprocs)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(join_s)
    assert not any(t.is_alive() for t in ts), "a rank hung past the join budget"
    return out, errs


def test_exchange_codec_roundtrip_and_stream_xor():
    """Adversarial planes through a real 2-rank exchange: decode exact,
    wire strictly below raw on compressible rounds, XOR engaging on a
    shape-stable stream, and reset_codec_state re-certifying after an
    epoch bump."""
    rng = np.random.default_rng(21)
    sparse = np.zeros((256, 4), np.uint32)
    sparse[rng.choice(256, 10, replace=False)] = 7
    dense = rng.integers(1, 2**32, (64, 4), dtype=np.uint32)

    def body(fab, rank):
        peer = 1 - rank
        seen = []
        for tick in range(4):
            # the shape-stable stream: same plane + a 1-word mutation, so
            # tick>0 sends can XOR against the previous payload
            plane = sparse.copy()
            plane[0, 0] = tick
            got = fab.exchange(
                100 + tick, {peer: [plane, dense]}, [peer], stream="s"
            )
            seen.append(got[peer])
            if tick == 1:
                fab.reset_codec_state()  # both ranks, same point
        return seen, fab.wire_stats(), fab.codec_epoch

    out, errs = _run_ranks(2, body, "codecrt")
    assert errs == [None, None], errs
    for seen, ws, epoch in out:
        for tick, (p, d) in enumerate(seen):
            ref = sparse.copy()
            ref[0, 0] = tick
            assert np.array_equal(p, ref) and np.array_equal(d, dense)
        assert ws["bytes_sent"] < ws["raw_bytes_sent"]
        # raw fallback exercised by the dense plane, compression by the rest
        assert ws["codec_counts"].get("raw", 0) >= 1
        assert sum(v for k, v in ws["codec_counts"].items() if k != "raw") >= 1
        # engine-driven reset (tick==1) on top of the constructor state
        assert epoch >= 1


def test_codec_off_ships_raw_frames():
    a = np.zeros((128, 4), np.uint32)

    def body(fab, rank):
        got = fab.exchange(5, {1 - rank: [a]}, [1 - rank])
        return got[1 - rank][0], fab.wire_stats()

    out, errs = _run_ranks(2, body, "codecoff", codec=False)
    assert errs == [None, None], errs
    for got, ws in out:
        assert np.array_equal(got, a)
        assert ws["bytes_sent"] == ws["raw_bytes_sent"]
        assert set(ws["codec_counts"]) <= {"raw"}


# -- fabric robustness: dead / silent peers (r15 satellite) -------------------


def test_kill_one_rank_surfaces_peer_lost_not_hang():
    """A rank dying mid-run must fail its peers' next receive with a
    typed FabricPeerLost naming the peer — promptly, not at timeout_ms,
    and distinguishable from a tag desync."""
    rounds_before_death = 2

    def body(fab, rank):
        peers = [p for p in range(3) if p != rank]
        for tick in range(6):
            if rank == 2 and tick == rounds_before_death:
                fab.close()  # simulated death: sockets gone mid-schedule
                return "died"
            fab.exchange(tick, {p: [np.arange(4, dtype=np.uint32)] for p in peers}, peers)
        return "done"

    t0 = time.monotonic()
    out, errs = _run_ranks(3, body, "kill1", timeout_ms=30_000)
    wall = time.monotonic() - t0
    assert out[2] == "died"
    for r in (0, 1):
        assert isinstance(errs[r], FabricError), (r, errs[r], out[r])
        assert "peer" in str(errs[r])
        assert "desync" not in str(errs[r])
    # the closed socket fails the read immediately — nowhere near the
    # 30 s timeout budget (a hang-then-timeout would take >= 30 s)
    assert wall < 20, wall


def test_stalled_peer_surfaces_fabric_timeout():
    """A live-but-silent peer (wedged, partitioned) must surface as
    FabricTimeout at timeout_ms — the pre-r15 behavior on builds without
    socket timeouts was an unbounded _recv_exact hang."""

    def body(fab, rank):
        payload = [np.arange(8, dtype=np.uint32)]
        fab.exchange(0, {1 - rank: payload}, [1 - rank])
        if rank == 1:
            time.sleep(2.5)  # wedged: never sends round 1
            return "stalled"
        fab.exchange(1, {1 - rank: payload}, [1 - rank])
        return "done"

    out, errs = _run_ranks(2, body, "stall", timeout_ms=700)
    assert out[1] == "stalled"
    assert isinstance(errs[0], FabricTimeout), (errs[0], out[0])
    assert "peer 1" in str(errs[0]) and "700 ms" in str(errs[0])


def test_encoded_item_refused_on_streamed_round():
    """A pre-encoded item on a STREAMED round would desync the two
    sides' XOR payload histories under matching epochs (the sender has
    no raw bytes to record) — the fabric must refuse loudly."""
    e = Encoded(CODEC_RAW, np.dtype(np.uint32), (4,),
                np.arange(4, dtype=np.uint32).tobytes(), 16)

    def body(fab, rank):
        fab.exchange(3, {1 - rank: [e]}, [1 - rank], stream="s")

    out, errs = _run_ranks(2, body, "encstream", timeout_ms=5_000)
    assert all(isinstance(x, ValueError) for x in errs), errs
    assert all("streamed round" in str(x) for x in errs)


def test_rows_false_skips_rows_attempt():
    """encode_array(rows=False): the engine's device summary already
    rejected ROWS — the host chooser must not re-scan for it (RUNS and
    the raw fallback stay measured)."""
    rng = np.random.default_rng(2)
    plane = np.zeros((1000, 4), np.uint32)
    plane[rng.choice(1000, 100, replace=False)] = rng.integers(
        1, 2**32, (100, 4), dtype=np.uint32
    )
    assert encode_array(plane).codec == CODEC_ROWS
    e = encode_array(plane, rows=False)
    assert e.codec != CODEC_ROWS
    d = decode_array(e.codec, e.dtype, e.shape, e.payload)
    assert np.array_equal(d, plane)


def test_encoded_passthrough_type():
    """Encoded items pass the fabric untouched (the device-sourced hot
    path) — also pinning the public tuple layout the engine builds."""
    e = Encoded(CODEC_RAW, np.dtype(np.uint32), (2, 2),
                np.arange(4, dtype=np.uint32).tobytes(), 16)

    def body(fab, rank):
        got = fab.exchange(9, {1 - rank: [e]}, [1 - rank])
        return got[1 - rank][0]

    out, errs = _run_ranks(2, body, "pass")
    assert errs == [None, None], errs
    for got in out:
        assert np.array_equal(got, np.arange(4, dtype=np.uint32).reshape(2, 2))
