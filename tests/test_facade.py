"""Facade tests (model: reference ringpop_test.go RingpopTestSuite — mocked
components where useful, real in-process clusters elsewhere)."""

import asyncio

import pytest

from ringpop_tpu.errors import EphemeralIdentityError, NotBootstrappedError
from ringpop_tpu.net import LocalChannel, LocalNetwork
from ringpop_tpu.options import InMemoryStats, Options
from ringpop_tpu.ringpop import Ringpop, State
from ringpop_tpu.swim.node import BootstrapOptions
from ringpop_tpu.util.clock import MockClock

from swim_utils import run, tick_all, converged


def make_ringpop(network, hostport, app="rp-test", stats=None, seed=0):
    ch = LocalChannel(network, hostport, app=app)
    opts = Options(stats_reporter=stats, clock=MockClock(1e6), seed=seed)
    return Ringpop(app, ch, opts)


async def boot_cluster(n=3, app="rp-test", stats_for_first=None):
    network = LocalNetwork()
    rps = [
        make_ringpop(
            network,
            f"127.0.0.1:{4000 + i}",
            app=app,
            stats=stats_for_first if i == 0 else None,
            seed=i,
        )
        for i in range(n)
    ]
    hosts = [f"127.0.0.1:{4000 + i}" for i in range(n)]

    async def boot(rp):
        await rp.bootstrap(BootstrapOptions(discover_provider=hosts, join_timeout=0.5))
        rp.node.gossip.stop()
        rp.node.healer.stop()

    await asyncio.gather(*(boot(rp) for rp in rps))
    nodes = [rp.node for rp in rps]
    for _ in range(60):
        await tick_all(nodes)
        if converged(nodes):
            break
    return network, rps


def test_lifecycle_states():
    async def main():
        network = LocalNetwork()
        rp = make_ringpop(network, "127.0.0.1:4000")
        assert rp.state == State.CREATED
        with pytest.raises(NotBootstrappedError):
            rp.lookup("k")
        await rp.bootstrap(BootstrapOptions(discover_provider=["127.0.0.1:4000"]))
        assert rp.state == State.READY
        assert rp.ready()
        assert rp.who_am_i() == "127.0.0.1:4000"
        assert rp.app() == "rp-test"
        assert rp.uptime() >= 0
        rp.destroy()
        assert rp.state == State.DESTROYED

    run(main())


def test_ephemeral_identity_refused():
    network = LocalNetwork()
    ch = LocalChannel(network, "127.0.0.1:0")
    rp = Ringpop("x", ch, Options(clock=MockClock()))
    with pytest.raises(EphemeralIdentityError):
        rp._init()


def test_channel_required():
    with pytest.raises(ValueError):
        Ringpop("x", None)


def test_membership_drives_ring():
    async def main():
        network, rps = await boot_cluster(3)
        for rp in rps:
            assert sorted(rp.ring.servers()) == sorted(r.who_am_i() for r in rps)
        # all rings agree -> same checksum
        assert len({rp.checksum() for rp in rps}) == 1

        # faulty member leaves the ring
        victim = rps[2]
        m = rps[0].node.memberlist.member(victim.who_am_i())
        rps[0].node.memberlist.make_faulty(victim.who_am_i(), m.incarnation)
        assert victim.who_am_i() not in rps[0].ring.servers()

    run(main())


def test_lookup_consistent_across_nodes():
    async def main():
        network, rps = await boot_cluster(3)
        for key in ("alpha", "beta", "gamma", "delta"):
            owners = {rp.lookup(key) for rp in rps}
            assert len(owners) == 1  # everyone agrees
        dests = rps[0].lookup_n("alpha", 2)
        assert len(dests) == 2 and len(set(dests)) == 2

    run(main())


def test_handle_or_forward_routes_to_owner():
    async def main():
        network, rps = await boot_cluster(3)
        service, endpoint = "rp-test", "/app/echo"

        # register an app endpoint on every node that reports who served it
        for rp in rps:
            me = rp.who_am_i()

            async def echo(body, headers, me=me):
                return {"served_by": me, "payload": body.get("payload")}

            rp.channel.register(service, endpoint, echo)

        key = "some-key"
        owner = rps[0].lookup(key)
        # pick a caller that does NOT own the key
        caller = next(rp for rp in rps if rp.who_am_i() != owner)

        handled, res = await caller.handle_or_forward(
            key, {"payload": 42}, service, endpoint
        )
        assert not handled
        assert res == {"served_by": owner, "payload": 42}

        # the owner itself is told to handle locally
        owner_rp = next(rp for rp in rps if rp.who_am_i() == owner)
        handled, res = await owner_rp.handle_or_forward(key, {}, service, endpoint)
        assert handled and res is None

    run(main())


def test_stats_emitted():
    async def main():
        stats = InMemoryStats()
        network, rps = await boot_cluster(2, stats_for_first=stats)
        rps[0].lookup("k")
        prefix = f"ringpop.{rps[0].who_am_i().replace(':', '_').replace('.', '_')}."
        assert any(k.startswith(prefix + "lookup") for k in stats.timers)
        assert any(k.startswith(prefix + "ping.send") for k in stats.counters)
        assert prefix + "ring.server-added" in stats.counters

    run(main())


def test_admin_endpoints():
    async def main():
        network, rps = await boot_cluster(2)
        client = LocalChannel(network, "127.0.0.1:9999")
        target = rps[0].who_am_i()

        res = await client.call(target, "ringpop", "/health", {}, timeout=1.0)
        assert res == {"ok": True}

        res = await client.call(target, "ringpop", "/admin/lookup", {"key": "k"}, timeout=1.0)
        assert res["dest"] == rps[0].lookup("k")

        res = await client.call(target, "ringpop", "/admin/stats", {}, timeout=1.0)
        assert res["state"] == "ready"
        assert len(res["membership"]["members"]) == 2
        assert sorted(res["ring"]["servers"]) == sorted(r.who_am_i() for r in rps)
        assert res["protocol"]["timing"]["count"] >= 1

    run(main())


def test_periodic_checksum_stat_timers():
    async def main():
        stats = InMemoryStats()
        network, rps = await boot_cluster(2, stats_for_first=stats)
        rp = rps[0]
        prefix = f"ringpop.{rp.who_am_i().replace(':', '_').replace('.', '_')}."
        # advance the mock clock past the stat period; timers fire and renew
        rp.node.clock.advance(5.5)
        assert prefix + "membership.checksum-periodic" in stats.gauges
        assert prefix + "ring.checksum-periodic" in stats.gauges
        before = stats.gauges[prefix + "membership.checksum-periodic"]
        rp.node.clock.advance(5.5)  # fires again (renewed timer)
        assert stats.gauges[prefix + "membership.checksum-periodic"] == before

    run(main())
