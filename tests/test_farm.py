"""FarmHash Fingerprint32 tests: scalar/batch agreement, stability vectors,
distribution sanity (model: reference hashring micro-benchmarks + the role the
hash plays in checksum comparison, swim/memberlist.go:86)."""

import random

import numpy as np
import pytest

from ringpop_tpu.hashing import fingerprint32, fingerprint32_batch
from ringpop_tpu.hashing.farm import pack_strings


def test_known_vectors_stable():
    # Pinned outputs: any change to these silently breaks wire/checksum compat
    # with deployed clusters, so they are frozen here.
    assert fingerprint32(b"") == 0xDC56D17A
    assert fingerprint32(b"a") == 0x3C973D4D
    assert fingerprint32(b"hello world") == 0x19A7581A
    assert fingerprint32(b"0123456789abcdefghijklmnopqrstuvwxyz") == 0xC8912CEE


def test_str_and_bytes_agree():
    assert fingerprint32("10.0.0.1:3000") == fingerprint32(b"10.0.0.1:3000")


@pytest.mark.parametrize("trial", range(3))
def test_batch_matches_scalar_all_length_classes(trial):
    rng = random.Random(trial)
    strs = [bytes(rng.randrange(256) for _ in range(l)) for l in range(0, 120)]
    rng.shuffle(strs)
    mat, lens = pack_strings(strs)
    batch = fingerprint32_batch(mat, lens)
    for s, b in zip(strs, batch):
        assert fingerprint32(s) == int(b)


def test_batch_empty():
    mat, lens = pack_strings([])
    assert fingerprint32_batch(mat, lens).shape == (0,)


def test_distribution_is_roughly_uniform():
    # ring placement relies on spread (hashring.go:148-154); crude chi-square
    keys = [f"10.0.0.{i}:30{j:02d}{k}" for i in range(40) for j in range(5) for k in range(5)]
    mat, lens = pack_strings(keys)
    h = fingerprint32_batch(mat, lens)
    counts, _ = np.histogram(h, bins=16, range=(0, 2**32))
    expected = len(keys) / 16
    chi2 = float(((counts - expected) ** 2 / expected).sum())
    assert chi2 < 50, counts  # 15 dof; 50 is a generous bound

    assert len(np.unique(h)) == len(keys)  # no collisions in this tiny set
