"""Resume-exact fleet checkpoints (r19 tentpole leg 2).

The claim under pin: a long-horizon scored sweep killed mid-flight and
restored from its orbax carry checkpoint reproduces the unbroken run's
per-scenario state digests AND score records bit-exactly — the carry
holds batched state (tick + PRNG position ride inside it), batched
telemetry counters, and the sidecar holds sweep progress plus the
already-fetched block records (native JSON scalars, value-exact round
trip).  The multi-process flavor (each process writing only its shards,
restore onto a DIFFERENT process count) is certified by ``make
fleet-smoke`` / simbench ``fleet_scale``; these tests pin the
single-process and virtual-mesh paths plus the carry store itself.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ringpop_tpu.sim import lifecycle, scenarios, snapshot
from ringpop_tpu.sim.montecarlo import make_fleet_mesh

N, K = 128, 16
PARAMS = dict(n=N, k=K, suspect_ticks=6, rng="counter")


def _grid():
    plan, meta = scenarios.scenario_grid(
        N, victims=[3, 9], doses=[0, 4], losses=(0.0, 0.1), churn_seed=777
    )
    return plan, meta, scenarios.grid_seeds(meta, 0)


def _sweep(**kw):
    params = lifecycle.LifecycleParams(**PARAMS)
    plan, meta, seeds = _grid()
    return scenarios.FleetSweep(
        params, plan, meta, seeds, horizon=48, journal_every=16, **kw
    )


def test_kill_and_restore_bit_exact(tmp_path):
    unbroken = _sweep().run()
    want_scores, want_digests = unbroken.scores(), unbroken.digests()

    sweep = _sweep()
    sweep.run(until_tick=32)
    ck = os.path.join(tmp_path, "ck")
    sweep.save(ck)
    del sweep

    params = lifecycle.LifecycleParams(**PARAMS)
    plan, meta, seeds = _grid()
    resumed = scenarios.FleetSweep.restore(ck, params, plan, meta, seeds)
    assert resumed.ticks_done == 32
    assert resumed.resumed["from_tick"] == 32
    resumed.run()
    assert resumed.digests() == want_digests
    assert resumed.scores() == want_scores
    # restore-proof header fields (OBSERVABILITY.md fleet schema)
    hp = resumed.header_params()
    assert hp["resumed"]["restored_process_count"] == 1
    assert hp["ticks_done"] == 48


def test_restore_onto_fleet_mesh_bit_exact(tmp_path):
    """A checkpoint saved unsharded restores onto the batch-sharded
    virtual mesh (the shardings come from the restore target, not the
    store) and continues digest-equal."""
    unbroken = _sweep().run()
    want = unbroken.digests()

    sweep = _sweep()
    sweep.run(until_tick=16)
    ck = os.path.join(tmp_path, "ck")
    sweep.save(ck)

    params = lifecycle.LifecycleParams(**PARAMS)
    plan, meta, seeds = _grid()
    mesh = make_fleet_mesh(8, (2, 4, 1))
    resumed = scenarios.FleetSweep.restore(ck, params, plan, meta, seeds, mesh=mesh)
    from jax.sharding import PartitionSpec as P

    assert resumed.mc.states.pcount.sharding.spec == P("batch", "node", "rumor")
    resumed.run()
    assert resumed.digests() == want
    assert resumed.scores() == unbroken.scores()


def test_save_mid_sweep_does_not_perturb(tmp_path):
    """Saving is observation, not interference: a sweep that checkpoints
    mid-flight and keeps going lands the unbroken digests."""
    unbroken = _sweep().run()
    sweep = _sweep()
    sweep.run(until_tick=16)
    sweep.save(os.path.join(tmp_path, "ck"))
    sweep.run()
    assert sweep.digests() == unbroken.digests()
    assert sweep.scores() == unbroken.scores()


def test_restore_refuses_wrong_config(tmp_path):
    sweep = _sweep()
    sweep.run(until_tick=16)
    ck = os.path.join(tmp_path, "ck")
    sweep.save(ck)
    plan, meta, seeds = _grid()
    wrong = lifecycle.LifecycleParams(n=N, k=K, suspect_ticks=7, rng="counter")
    with pytest.raises(ValueError, match="checkpoint was taken with"):
        scenarios.FleetSweep.restore(ck, wrong, plan, meta, seeds)
    with pytest.raises(ValueError, match="sidecars"):
        scenarios.FleetSweep.restore(
            os.path.join(tmp_path, "nope"),
            lifecycle.LifecycleParams(**PARAMS), plan, meta, seeds,
        )


def test_run_refuses_off_boundary_checkpoint_target():
    sweep = _sweep()
    with pytest.raises(ValueError, match="block boundary"):
        sweep.run(until_tick=17)


def test_carry_orbax_round_trip_nested(tmp_path):
    """save_carry_orbax/load_carry_orbax: nested pytree with None legs
    round-trips bit-exactly; a shape drift refuses."""
    from ringpop_tpu.sim.telemetry import TelemetryState, zeros

    params = lifecycle.LifecycleParams(n=64, k=16)
    tel = zeros(params)  # suspects_by_tier None: structure, not leaves
    carry = {
        "states": {"x": jnp.arange(12, dtype=jnp.int32).reshape(3, 4)},
        "telemetry": tel,
        "first": jnp.asarray([1, -1, 3], jnp.int32),
    }
    path = os.path.join(tmp_path, "carry")
    snapshot.save_carry_orbax(path, carry)
    out = snapshot.load_carry_orbax(path, carry)
    assert isinstance(out["telemetry"], TelemetryState)
    assert out["telemetry"].suspects_by_tier is None
    for a, b in zip(jax.tree.leaves(carry), jax.tree.leaves(out)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    bad = dict(carry, first=jnp.zeros(5, jnp.int32))
    with pytest.raises(Exception):  # orbax raises on structure/shape drift
        snapshot.load_carry_orbax(path, bad)
