"""Block-sharded scenario fleets (r19 tentpole leg 1): the batch axis on
the mesh.

The claim under pin: a fleet whose ``[B, ...]`` arrays shard their
REPLICA axis over a ``make_fleet_mesh`` device mesh (states, telemetry
accumulator, stacked fault legs — all via the canonical partition table)
runs bit-identically, scenario for scenario, to the unsharded fleet:
same per-member state digests, same telemetry block records, same
first-detection ticks.  Scenarios are independent, so batch sharding
adds no collectives that could reassociate anything — the certificate is
exact equality, not tolerance.

Includes the r18 follow-up: topology overlays (``scenario_grid(
overlays=...)``) through the SHARDED fleet — previously only the flat
fleet had a sharded twin pin.
"""

from __future__ import annotations

import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from ringpop_tpu.sim import chaos, lifecycle, scenarios, telemetry
from ringpop_tpu.sim.montecarlo import (
    MonteCarlo,
    fleet_faults_shardings,
    fleet_state_shardings,
    make_fleet_mesh,
)

N, K = 128, 16
PARAMS = dict(n=N, k=K, suspect_ticks=6, rng="counter")


@pytest.fixture(scope="module")
def fleet_mesh():
    # 8 virtual CPU devices (conftest): 2-way batch x 4-way node
    return make_fleet_mesh(8, (2, 4, 1))


@pytest.fixture(scope="module")
def grid():
    rng = np.random.default_rng(0)
    victims = sorted(rng.choice(N, size=2, replace=False).tolist())
    plan, meta = scenarios.scenario_grid(
        N, victims=victims, doses=[0, 4], losses=(0.0, 0.1), churn_seed=777
    )
    return victims, plan, meta, scenarios.grid_seeds(meta, 0)


def test_fleet_state_shardings_batch_axis(fleet_mesh):
    fs = fleet_state_shardings(fleet_mesh, k=32)
    assert fs.pcount.spec == P("batch", "node", "rumor")
    assert fs.base_status.spec == P("batch", "node")
    assert fs.tick.spec == P("batch")
    assert fs.r_subject.spec == P("batch", "rumor")


def test_fleet_faults_shardings_batched_vs_shared_legs(fleet_mesh, grid):
    _, plan, _, _ = grid
    sh = fleet_faults_shardings(plan, fleet_mesh)
    # stacked legs carry the batch prefix over their canonical spec
    assert sh.base_up.spec == P("batch", "node")
    assert sh.drop_rate.spec == P("batch")
    # legs no member set stay None
    assert (plan.reach is None) == (sh.reach is None)
    # a SOLO plan's legs keep the canonical placement, no batch prefix
    solo = chaos.scenario_plan("churn", N, seed=0, horizon=64)
    ssh = fleet_faults_shardings(solo, fleet_mesh)
    assert solo.crash_tick is not None
    assert ssh.crash_tick.spec == P("node")


def test_sharded_fleet_digest_equal_per_scenario(fleet_mesh, grid):
    """run() + fetch_telemetry through the batch-sharded mesh: every
    per-scenario record — digest AND every counter — equals the
    unsharded fleet's."""
    params = lifecycle.LifecycleParams(**PARAMS)
    _, plan, _, seeds = grid
    mc_u = MonteCarlo(params, seeds, telemetry=True)
    mc_s = MonteCarlo(params, seeds, telemetry=True, mesh=fleet_mesh)
    # placement engaged: the batch axis is genuinely sharded
    assert mc_s.states.pcount.sharding.spec == P("batch", "node", "rumor")
    mc_u.run(24, plan)
    mc_s.run(24, plan)
    for ru, rs in zip(mc_u.fetch_telemetry(plan), mc_s.fetch_telemetry(plan)):
        assert ru == rs, (ru["scenario_id"],)


def test_sharded_detection_loop_equal(fleet_mesh, grid):
    """run_until_detected (the while-loop program, telemetry carried)
    lands identical first-detection ticks and state digests sharded vs
    unsharded."""
    params = lifecycle.LifecycleParams(**PARAMS)
    victims, plan, _, seeds = grid
    mc_u = MonteCarlo(params, seeds, telemetry=True)
    mc_s = MonteCarlo(params, seeds, telemetry=True, mesh=fleet_mesh)
    tu, du = mc_u.run_until_detected(victims, plan, max_ticks=256, check_every=4)
    ts, ds = mc_s.run_until_detected(victims, plan, max_ticks=256, check_every=4)
    assert [int(t) for t in tu] == [int(t) for t in ts]
    assert list(du) == list(ds)
    assert mc_u.fetch_telemetry(plan) == mc_s.fetch_telemetry(plan)


def test_overlay_grid_sharded_twin(fleet_mesh):
    """r18 topology overlays through the SHARDED fleet: a
    ``scenario_grid(overlays=...)`` batch (tier legs, zone-loss windows)
    on the batch-sharded mesh is digest-equal per member to its
    unsharded twin — today's pin extends the flat-fleet-only one."""
    from ringpop_tpu.sim import topology

    params = lifecycle.LifecycleParams(**PARAMS)
    overlays = [
        ("none", None),
        ("zone_loss", topology.topo_scenario_plan("zone_loss", N, seed=1, horizon=64)),
    ]
    plan, meta = scenarios.scenario_grid(
        N, victims=[3, 9], doses=[0, 4], losses=(0.0,),
        overlays=overlays, churn_seed=7,
    )
    seeds = scenarios.grid_seeds(meta, 0)
    mc_u = MonteCarlo(params, seeds, telemetry=True, telemetry_tiers=True)
    mc_s = MonteCarlo(
        params, seeds, telemetry=True, telemetry_tiers=True, mesh=fleet_mesh
    )
    mc_u.run(32, plan)
    mc_s.run(32, plan)
    ru, rs = mc_u.fetch_telemetry(plan), mc_s.fetch_telemetry(plan)
    assert [r["overlay"] for r in (dict(m, **r) for m, r in zip(meta, ru))]
    for m, (a, b) in zip(meta, zip(ru, rs)):
        assert a == b, (m["overlay"], m["scenario_id"])
    # the per-tier keys actually rode the sharded fetch
    assert any(k.startswith("suspects_") for k in ru[0])


def test_slice_plan_matches_index_plan(grid):
    _, plan, _, _ = grid
    b = chaos.plan_batch_size(plan)
    part = chaos.slice_plan(plan, 1, 3)
    assert chaos.plan_batch_size(part) == 2
    for j, src in enumerate(range(1, 3)):
        want = chaos.index_plan(plan, src)
        got = chaos.index_plan(part, j)
        for f in want._fields:
            w, g = getattr(want, f), getattr(got, f)
            assert (w is None) == (g is None), f
            if w is not None:
                np.testing.assert_array_equal(np.asarray(w), np.asarray(g), err_msg=f)
    with pytest.raises(ValueError, match="slice"):
        chaos.slice_plan(plan, 3, 1)
    # full-range slice round-trips the batch size
    assert chaos.plan_batch_size(chaos.slice_plan(plan, 0, b)) == b


def test_fleet_shard_put_gather_round_trip(fleet_mesh):
    """partition.fleet_shard_put places a local batch block as a global
    batch-sharded array; fleet_host_gather inverts it (single-process:
    local == all)."""
    from jax.sharding import Mesh

    from ringpop_tpu.parallel.partition import fleet_host_gather, fleet_shard_put

    mesh = Mesh(np.asarray(jax.devices("cpu")[:8]), ("batch",))
    tree = {
        "a": np.arange(8 * 6, dtype=np.int32).reshape(8, 6),
        "b": np.arange(8, dtype=np.float32),
    }
    placed = fleet_shard_put(tree, mesh, 8)
    assert placed["a"].sharding.spec == P("batch", None)
    back = fleet_host_gather(placed)
    np.testing.assert_array_equal(back["a"], tree["a"])
    np.testing.assert_array_equal(back["b"], tree["b"])
