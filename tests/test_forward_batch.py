"""Batched forwarding plane + quorum replica reads (forward/batch.py, r17)."""

import asyncio

import numpy as np
import pytest

from ringpop_tpu.forward.batch import (
    BatchForwarder,
    BlockRouter,
    HOPS_HEADER,
    MaxHopsExceededError,
    QuorumReader,
    quorum_chaos_run,
    quorum_size,
    rank_of_hashes,
)
from ringpop_tpu.net.channel import (
    CallError,
    LocalChannel,
    LocalNetwork,
    decode_array,
    encode_array,
)


def _run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


def _ring(t=32, n_servers=4, seed=0):
    rng = np.random.default_rng(seed)
    tokens = np.sort(rng.choice(2**32 - 2, size=t, replace=False).astype(np.uint32))
    owners = (np.arange(t) % n_servers).astype(np.int32)
    return tokens, owners


def _lookup_server(net, addr, tokens, owners, gen=0, calls=None):
    """One in-process serve node answering /lookup from (tokens, owners)."""
    chan = LocalChannel(net, addr, app="srv")

    async def handle(body, headers):
        if calls is not None:
            calls.append((addr, len(decode_array(body["h"], "<u4")), headers))
        h = decode_array(body["h"], "<u4")
        idx = np.searchsorted(tokens, h, side="left")
        idx = np.where(idx >= tokens.shape[0], 0, idx)
        return {"o": encode_array(owners[idx], "json", "<i4"), "gen": gen}

    chan.register("serve", "/lookup", handle)
    return chan


def test_quorum_size_is_majority_of_r_plus_one():
    assert quorum_size(1) == 1
    assert quorum_size(2) == 2
    assert quorum_size(3) == 2
    assert quorum_size(4) == 3
    assert quorum_size(5) == 3


def test_rank_of_hashes_equal_blocks_and_wrap():
    tokens = np.array([10, 20, 30, 40, 50, 60, 70, 80], np.uint32)
    ranks = rank_of_hashes(tokens, np.array([5, 25, 45, 65, 90], np.uint32), 4)
    # starts: idx 0, 2, 4, 6, wrap->0
    assert list(ranks) == [0, 1, 2, 3, 0]
    with pytest.raises(ValueError):
        rank_of_hashes(tokens[:6], np.array([5], np.uint32), 4)


def test_forward_batch_one_rpc_per_owner_and_counters():
    """The coalescing claim: forwarding B keys to one owner is ONE RPC
    with all B keys aboard, counted."""
    net = LocalNetwork()
    tokens, owners = _ring()
    calls = []
    _lookup_server(net, "s:1", tokens, owners, gen=3, calls=calls)
    client = LocalChannel(net, "c:1")
    fwd = BatchForwarder(client)

    hashes = np.arange(100, dtype=np.uint32) * 7919
    rows, gen = _run(fwd.forward_batch("s:1", hashes))
    assert gen == 3 and rows.shape == (100,)
    assert len(calls) == 1 and calls[0][1] == 100
    assert fwd.rpcs == 1 and fwd.keys_forwarded == 100
    # the forwarded + hop headers ride the frame
    hdrs = calls[0][2]
    assert hdrs.get("ringpop-forwarded") == "true"
    assert hdrs.get(HOPS_HEADER) == "1"


def test_forward_batch_retry_backoff_then_failure():
    net = LocalNetwork()
    client = LocalChannel(net, "c:1")
    fwd = BatchForwarder(
        client, max_retries=2, retry_delays=(0.001, 0.002), timeout=0.05
    )
    with pytest.raises(CallError):
        _run(fwd.forward_batch("dead:1", np.array([1], np.uint32)))
    assert fwd.rpcs == 3  # initial + 2 retries
    assert fwd.retries == 2 and fwd.batches_failed == 1


def test_forward_batch_max_hop_guard():
    net = LocalNetwork()
    client = LocalChannel(net, "c:1")
    fwd = BatchForwarder(client, max_hops=3)
    with pytest.raises(MaxHopsExceededError):
        _run(fwd.forward_batch("s:1", np.array([1], np.uint32), hops=3))
    assert fwd.rpcs == 0  # the guard fires before the wire


def test_block_router_splits_local_remote_one_rpc_per_owner():
    """B keys spanning 4 rank blocks from rank 0: local block answers
    in-process, the 3 remote blocks cost exactly 3 RPCs."""
    net = LocalNetwork()
    tokens, owners = _ring(t=32, n_servers=4)
    calls = []
    addrs = [f"s:{r}" for r in range(4)]
    for r in range(1, 4):
        _lookup_server(net, addrs[r], tokens, owners, gen=5, calls=calls)
    client = LocalChannel(net, "c:1")
    fwd = BatchForwarder(client)

    def local_lookup(h, n):
        idx = np.searchsorted(tokens, h, side="left")
        idx = np.where(idx >= tokens.shape[0], 0, idx)
        return owners[idx], 5

    router = BlockRouter(0, 4, lambda: tokens, local_lookup, addrs, fwd)
    rng = np.random.default_rng(1)
    hashes = rng.integers(0, 2**32, size=256, dtype=np.uint32)
    got, gens = _run(router.route(hashes))
    # oracle: every key answered as if one process owned the whole ring
    idx = np.searchsorted(tokens, hashes, side="left")
    idx = np.where(idx >= tokens.shape[0], 0, idx)
    assert np.array_equal(got, owners[idx])
    assert (gens == 5).all()
    ranks = rank_of_hashes(tokens, hashes, 4)
    n_remote_owners = len(set(ranks.tolist()) - {0})
    assert len(calls) == n_remote_owners  # O(owners), not O(keys)
    assert fwd.rpcs == n_remote_owners
    assert router.keys_local == int((ranks == 0).sum())
    assert router.keys_forwarded == int((ranks != 0).sum())


def test_block_router_handler_reforwards_with_hop_bump_and_loop_dies():
    """A router that believes another rank owns its own block: every
    forward lands back on itself, the hop counter climbs per forward, and
    the loop dies at the guard after EXACTLY max_hops RPCs (remote-handler
    errors are not retried — a loop must not cost 3^hops)."""
    net = LocalNetwork()
    tokens, owners = _ring(t=8, n_servers=2)
    addrs = ["a:1", "b:1"]
    chan = LocalChannel(net, addrs[0])
    fwd = BatchForwarder(chan, endpoint="/fwd", max_hops=4)

    def never_local(h, n):  # pragma: no cover - router never answers
        raise AssertionError("should not answer locally")

    # the router sits on a:1 but claims rank 1's block — every rank-0 key
    # forwards to addrs[0] == itself: a pure routing loop
    router = BlockRouter(1, 2, lambda: tokens, never_local, addrs, fwd)
    chan.register("serve", "/fwd", router.handler())

    async def drive():
        h = np.array([int(tokens[0]) - 1], np.uint32)  # rank 0's block
        with pytest.raises(CallError) as ei:
            await router.route(h)
        # the deepest hop's guard surfaces through the channel
        assert "routing loop" in str(ei.value)

    _run(drive())
    # hops 0..3 each cost one RPC; the guard at hops=4 fires pre-wire
    assert fwd.rpcs == 4


def test_block_router_multi_hop_preserves_per_key_generations():
    """A re-forwarded batch that mixes answerers at DIFFERENT ring
    generations must report each key's ACTUAL answering generation — the
    handler ships the per-key array, never a collapsed max."""
    net = LocalNetwork()
    tokens, owners = _ring(t=32, n_servers=4, seed=5)
    addrs = ["ra:1", "rb:1"]
    chans = [LocalChannel(net, a) for a in addrs]
    fwds = [BatchForwarder(c, endpoint="/fwd") for c in chans]

    def lookup_at(gen):
        def local_lookup(h, n):
            idx = np.searchsorted(tokens, h, side="left")
            idx = np.where(idx >= tokens.shape[0], 0, idx)
            return owners[idx], gen

        return local_lookup

    ra = BlockRouter(0, 2, lambda: tokens, lookup_at(5), addrs, fwds[0])
    rb = BlockRouter(1, 2, lambda: tokens, lookup_at(6), addrs, fwds[1])
    chans[0].register("serve", "/fwd", ra.handler())
    chans[1].register("serve", "/fwd", rb.handler())

    client = LocalChannel(net, "cl:1")
    cf = BatchForwarder(client, endpoint="/fwd")
    rng = np.random.default_rng(9)
    hashes = rng.integers(0, 2**32, size=128, dtype=np.uint32)
    ranks = rank_of_hashes(tokens, hashes, 2)
    assert (ranks == 0).any() and (ranks == 1).any()

    # client -> ra: ra answers its block at gen 5 and RE-FORWARDS rank-1
    # keys to rb (gen 6) — two answerers, one response
    rows, gens = _run(cf.forward_batch(addrs[0], hashes))
    assert isinstance(gens, np.ndarray)
    assert (gens[ranks == 0] == 5).all()
    assert (gens[ranks == 1] == 6).all()
    idx = np.searchsorted(tokens, hashes, side="left")
    idx = np.where(idx >= tokens.shape[0], 0, idx)
    assert np.array_equal(rows, owners[idx])


def test_quorum_reader_acks_and_agreement():
    net = LocalNetwork()
    servers = [f"q:{i}" for i in range(5)]
    from ringpop_tpu.ops.ring_ops import build_ring_tokens

    jt, jo = build_ring_tokens(servers, 8)
    tokens, owners = np.asarray(jt, np.uint32), np.asarray(jo, np.int32)
    for s in servers:
        _lookup_server(net, s, tokens, owners)
    client = LocalChannel(net, "c:9")
    fwd = BatchForwarder(client, max_retries=0, timeout=0.05)
    reader = QuorumReader(fwd, servers, r=3)
    hashes = np.arange(64, dtype=np.uint32) * 65537

    wave = _run(reader.quorum_wave(tokens, owners, 5, hashes))
    assert wave["acks_min"] == 3 and wave["quorum_ok_frac"] == 1.0
    assert wave["full_ack_frac"] == 1.0 and wave["answers_agree"]
    assert wave["rpcs"] <= 5  # one per owning server, never per key

    # kill a PRIMARY owner: quorum (2 of 3) must hold, full acks must dip
    from ringpop_tpu.ops.ring_ops import host_lookup_n

    victim = int(host_lookup_n(tokens, owners, hashes, 1, 5)[0, 0])
    net.black_hole(servers[victim])
    wave2 = _run(reader.quorum_wave(tokens, owners, 5, hashes))
    assert wave2["quorum_ok_frac"] == 1.0 and wave2["acks_min"] == 2
    assert wave2["full_ack_frac"] < 1.0


@pytest.mark.slow
def test_quorum_chaos_run_scores_recovery():
    """The full harness: staggered owner kills with restarts — quorum
    holds throughout, full-ack recovery is scored per crash through
    chaos.score_blocks, and the RPC pricing stays O(owners)."""
    rec = quorum_chaos_run(horizon=24, keys_per_tick=48, seed=0)
    assert rec["owners_killed"] and rec["quorum_held"] and rec["answers_agree"]
    assert rec["score"]["quorum_ok_frac_min"] == 1.0
    assert rec["score"]["quorum_acks_min"] >= rec["quorum"]
    # every crash's full-replication recovery was observed (ttd not null)
    ttd = rec["score"]["time_to_detect"]
    assert ttd and all(v is not None for _, v in ttd)
    assert rec["rpcs"] < rec["rpcs_naive"]  # strictly below naive
