"""Forwarding, router, replicator and adapter tests (model: reference
forward/forwarder_test.go, router/router_test.go, replica/replicator_test.go,
test/remoteservice tests — mocked senders steer local-vs-remote paths)."""

import asyncio

import pytest

from ringpop_tpu.adapter import ServiceAdapter, keyed
from ringpop_tpu.forward import (
    Forwarder,
    Options as ForwardOptions,
    has_forwarded_header,
    set_forwarded_header,
)
from ringpop_tpu.forward.request_sender import DestinationsDivergedError, MaxRetriesError
from ringpop_tpu.net import LocalChannel, LocalNetwork
from ringpop_tpu.replica import (
    FanoutMode,
    NotEnoughResponsesError,
    Options as ReplicaOptions,
    Replicator,
)
from ringpop_tpu.router import Router
from ringpop_tpu.swim.node import BootstrapOptions

from swim_utils import run
from test_facade import boot_cluster


class FakeSender:
    """Mock of the forward.Sender interface (model: the reference's
    mockery-generated mocks, forward/mock_sender_test.go)."""

    def __init__(self, me="me:1", lookups=None):
        self.me = me
        self.lookups = lookups or {}

    def who_am_i(self):
        return self.me

    def lookup(self, key):
        return self.lookups.get(key, "dest:1")

    def lookup_n(self, key, n):
        v = self.lookups.get(key, "dest:1")
        return [v] if isinstance(v, str) else list(v)[:n]


def test_forwarded_header_helpers():
    h = set_forwarded_header(None)
    assert has_forwarded_header(h)
    assert not has_forwarded_header({})
    assert not has_forwarded_header(None)
    # (parity: forwarder.go:196-203 only the exact value counts)
    assert not has_forwarded_header({"ringpop-forwarded": "yes"})


def test_forward_success_and_header_set():
    async def main():
        network = LocalNetwork()
        server = LocalChannel(network, "dest:1")
        seen = {}

        async def handler(body, headers):
            seen.update(headers=headers, body=body)
            return {"ok": 1}

        server.register("svc", "/ep", handler)
        client = LocalChannel(network, "me:1")
        fwd = Forwarder(FakeSender(lookups={"k": "dest:1"}), client)
        res = await fwd.forward_request({"a": 1}, "dest:1", "svc", "/ep", ["k"])
        assert res == {"ok": 1}
        assert has_forwarded_header(seen["headers"])
        assert fwd.inflight == 0

    run(main())


def test_forward_retries_then_succeeds():
    async def main():
        network = LocalNetwork()
        client = LocalChannel(network, "me:1")
        calls = {"n": 0}

        # destination comes up only after the first attempt fails
        async def handler(body, headers):
            return {"ok": calls["n"]}

        fwd = Forwarder(FakeSender(lookups={"k": "dest:1"}), client)
        opts = ForwardOptions(max_retries=2, retry_schedule=(0.01, 0.01), timeout=0.2)

        async def bring_up_later():
            await asyncio.sleep(0.005)
            server = LocalChannel(network, "dest:1")
            server.register("svc", "/ep", handler)

        task = asyncio.ensure_future(bring_up_later())
        res = await fwd.forward_request({"a": 1}, "dest:1", "svc", "/ep", ["k"], opts)
        assert res == {"ok": 0}
        await task

    run(main())


def test_forward_max_retries_exhausted():
    async def main():
        network = LocalNetwork()
        client = LocalChannel(network, "me:1")
        fwd = Forwarder(FakeSender(lookups={"k": "gone:9"}), client)
        opts = ForwardOptions(max_retries=2, retry_schedule=(0.001, 0.001), timeout=0.05)
        with pytest.raises(MaxRetriesError):
            await fwd.forward_request({}, "gone:9", "svc", "/ep", ["k"], opts)

    run(main())


def test_forward_aborts_when_destinations_diverge():
    async def main():
        network = LocalNetwork()
        client = LocalChannel(network, "me:1")
        sender = FakeSender(lookups={"k1": "gone:9", "k2": "gone:9"})
        fwd = Forwarder(sender, client)
        opts = ForwardOptions(max_retries=3, retry_schedule=(0.001,), timeout=0.05)

        # after the first failure the keys hash to different owners
        orig_attempt = {}

        async def diverge():
            await asyncio.sleep(0.002)
            sender.lookups = {"k1": "a:1", "k2": "b:2"}

        task = asyncio.ensure_future(diverge())
        with pytest.raises(DestinationsDivergedError):
            await fwd.forward_request({}, "gone:9", "svc", "/ep", ["k1", "k2"], opts)
        await task

    run(main())


def test_forward_reroute_retry_follows_new_owner():
    async def main():
        network = LocalNetwork()
        client = LocalChannel(network, "me:1")
        newdest = LocalChannel(network, "new:1")

        async def handler(body, headers):
            return {"served": "new"}

        newdest.register("svc", "/ep", handler)
        sender = FakeSender(lookups={"k": "gone:9"})
        fwd = Forwarder(sender, client)
        opts = ForwardOptions(
            max_retries=2, retry_schedule=(0.001,), timeout=0.05, reroute_retries=True
        )

        async def move():
            await asyncio.sleep(0.002)
            sender.lookups = {"k": "new:1"}

        task = asyncio.ensure_future(move())
        res = await fwd.forward_request({}, "gone:9", "svc", "/ep", ["k"], opts)
        assert res == {"served": "new"}
        await task

    run(main())


# ---------------------------------------------------------------------------
# Router
# ---------------------------------------------------------------------------


class FakeRingpop:
    def __init__(self, me, owners):
        self.me = me
        self.owners = owners
        self.listeners = []

    def lookup(self, key):
        return self.owners[key]

    def who_am_i(self):
        return self.me

    def register_listener(self, l):
        self.listeners.append(l)


class Factory:
    def __init__(self):
        self.made = []

    def get_local_client(self):
        return "LOCAL"

    def make_remote_client(self, hostport):
        self.made.append(hostport)
        return f"REMOTE({hostport})"


def test_router_local_vs_remote_and_cache():
    rp = FakeRingpop("a:1", {"k1": "a:1", "k2": "b:2"})
    f = Factory()
    router = Router(rp, f)

    client, is_local = router.get_client("k1")
    assert client == "LOCAL" and is_local

    client, is_local = router.get_client("k2")
    assert client == "REMOTE(b:2)" and not is_local
    router.get_client("k2")
    assert f.made == ["b:2"]  # cached, factory called once


def test_router_batch_default_and_serve_source():
    """get_client_batch resolves a whole key wave in one lookup — through
    the fallback scalar/batch ring path, or through an injected
    serve-tier lookup source (the shared device ring's resolver)."""
    rp = FakeRingpop("a:1", {"k1": "a:1", "k2": "b:2", "k3": "b:2"})
    f = Factory()
    router = Router(rp, f)
    out = router.get_client_batch(["k1", "k2", "k3"])
    assert out == [("LOCAL", True), ("REMOTE(b:2)", False), ("REMOTE(b:2)", False)]
    assert f.made == ["b:2"]  # one remote client for the whole wave
    assert router.get_client_batch([]) == []

    # injected source: the batch resolver wins over ringpop.lookup
    calls = []

    def serve_source(keys):
        calls.append(list(keys))
        return ["c:3" for _ in keys]

    f2 = Factory()
    router2 = Router(rp, f2, lookup_source=serve_source)
    out2 = router2.get_client_batch(["k1", "k2"])
    assert calls == [["k1", "k2"]]
    assert out2 == [("REMOTE(c:3)", False), ("REMOTE(c:3)", False)]
    assert f2.made == ["c:3"]
    # scalar path unchanged: still ringpop.lookup
    assert router2.get_client("k1") == ("LOCAL", True)


def test_router_evicts_on_faulty():
    from ringpop_tpu.swim import events as swim_ev
    from ringpop_tpu.swim.member import Change, FAULTY

    rp = FakeRingpop("a:1", {"k2": "b:2"})
    f = Factory()
    router = Router(rp, f)
    router.get_client("k2")
    assert f.made == ["b:2"]

    router.handle_event(
        swim_ev.MemberlistChangesAppliedEvent(
            changes=[Change(address="b:2", incarnation=1, status=FAULTY)]
        )
    )
    router.get_client("k2")
    assert f.made == ["b:2", "b:2"]  # cache was evicted, factory re-called


# ---------------------------------------------------------------------------
# Replicator
# ---------------------------------------------------------------------------


def _replica_network(dests=("a:1", "b:2", "c:3")):
    network = LocalNetwork()
    served = []
    for d in dests:
        ch = LocalChannel(network, d, app="svc")

        async def handler(body, headers, d=d):
            served.append(d)
            return {"from": d}

        ch.register("svc", "/op", handler)
    client = LocalChannel(network, "me:1", app="svc")
    return network, client, served


def test_replicator_parallel_quorum():
    async def main():
        network, client, served = _replica_network()
        sender = FakeSender(me="me:1", lookups={"k": ["a:1", "b:2", "c:3"]})
        rep = Replicator(sender, client)
        responses = await rep.write(["k"], {"v": 1}, "/op")
        assert len(responses) == 3  # w=3 of n=3
        assert sorted(r.body["from"] for r in responses) == ["a:1", "b:2", "c:3"]
        assert sorted(served) == ["a:1", "b:2", "c:3"]

    run(main())


def test_replicator_read_needs_only_r():
    async def main():
        network, client, served = _replica_network(dests=("a:1",))  # only one up
        sender = FakeSender(me="me:1", lookups={"k": ["a:1", "b:2", "c:3"]})
        rep = Replicator(sender, client)
        fopts = ForwardOptions(max_retries=0, retry_schedule=(0.001,), timeout=0.05)
        responses = await rep.read(["k"], {}, "/op", fopts=fopts)  # r=1
        assert len(responses) >= 1

    run(main())


def test_replicator_write_fails_below_quorum():
    async def main():
        network, client, served = _replica_network(dests=("a:1",))
        sender = FakeSender(me="me:1", lookups={"k": ["a:1", "b:2", "c:3"]})
        rep = Replicator(sender, client)
        fopts = ForwardOptions(max_retries=0, retry_schedule=(0.001,), timeout=0.05)
        with pytest.raises(NotEnoughResponsesError):
            await rep.write(["k"], {}, "/op", fopts=fopts)  # needs 3, only 1 up

    run(main())


def test_replicator_serial_modes():
    async def main():
        for mode in (FanoutMode.SERIAL_SEQUENTIAL, FanoutMode.SERIAL_BALANCED):
            network, client, served = _replica_network()
            sender = FakeSender(me="me:1", lookups={"k": ["a:1", "b:2", "c:3"]})
            rep = Replicator(sender, client)
            responses = await rep.read(
                ["k"], {}, "/op", opts=ReplicaOptions(fanout_mode=mode)
            )
            # serial modes stop at r=1 responses
            assert len(responses) == 1
            assert len(served) == 1

    run(main())


# ---------------------------------------------------------------------------
# Service adapter (codegen equivalent)
# ---------------------------------------------------------------------------


def test_adapter_routes_by_key_with_loop_guard():
    async def main():
        network, rps = await boot_cluster(3, app="adapter-test")
        service = "adapter-test"
        adapters = []
        for rp in rps:
            me = rp.who_am_i()

            async def handler(body, me=me):
                return {"handled_by": me, "user": body["user"]}

            adapter = ServiceAdapter(
                rp,
                rp.channel,
                service,
                endpoints={"/user/get": (lambda b: b["user"], handler)},
                forward_options=ForwardOptions(max_retries=0, timeout=1.0),
            )
            adapters.append(adapter)

        key = "user-42"
        owner = rps[0].lookup(key)

        # call through a NON-owner's wire endpoint: must be forwarded once
        non_owner = next(rp for rp in rps if rp.who_am_i() != owner)
        client = LocalChannel(network, "ext:1")
        res = await client.call(
            non_owner.who_am_i(), service, "/user/get", {"user": key}, timeout=2.0
        )
        assert res["handled_by"] == owner

        # adapter client-side call also lands on the owner
        res = await adapters[0].call("/user/get", {"user": key})
        assert res["handled_by"] == owner

    run(main())
