"""On-device FarmHash + fused keyed routing: bit-exactness against the
scalar reference (which the native C++ core and host ring already match)."""

import numpy as np
import pytest

import jax.numpy as jnp

from ringpop_tpu.hashing.farm import fingerprint32, pack_strings
from ringpop_tpu.ops.hash_ops import fingerprint32_device, keyed_owner_lookup
from ringpop_tpu.ops.hash_pallas import fingerprint32_pallas
from ringpop_tpu.ops.ring_ops import build_ring_tokens


def _corpus(seed=0, n_rand=4):
    rng = np.random.default_rng(seed)
    strings = []
    # every length class boundary: 0..25, plus >24 loop counts 1..6
    for L in list(range(0, 26)) + [30, 40, 41, 60, 61, 80, 99, 100, 120, 127]:
        for _ in range(n_rand):
            strings.append(bytes(rng.integers(0, 256, size=L, dtype=np.uint8)))
    # realistic ring keys
    strings += [f"10.3.{i % 256}.{i % 40}:31{i % 100:02d}#{i}".encode() for i in range(128)]
    return strings


def test_device_hash_bitexact():
    strings = _corpus(seed=2)
    mat, lens = pack_strings(strings)
    got = np.asarray(fingerprint32_device(mat, lens))
    want = np.array([fingerprint32(s) for s in strings], dtype=np.uint32)
    assert (got == want).all()


def test_pallas_hash_bitexact_interpret():
    strings = _corpus(seed=3)
    mat, lens = pack_strings(strings)
    got = np.asarray(fingerprint32_pallas(mat, lens, interpret=True))
    want = np.array([fingerprint32(s) for s in strings], dtype=np.uint32)
    assert (got == want).all()


def test_pallas_auto_falls_back_and_is_bitexact():
    """fingerprint32_auto must yield correct hashes whether or not the
    compiled Pallas kernel lowers on this backend (on CPU, non-interpret
    pallas_call may or may not compile — either branch must be exact)."""
    from ringpop_tpu.ops import hash_pallas

    strings = _corpus(seed=4)
    mat, lens = pack_strings(strings)
    want = np.array([fingerprint32(s) for s in strings], dtype=np.uint32)
    got = np.asarray(hash_pallas.fingerprint32_auto(mat, lens))
    assert (got == want).all()
    assert mat.shape[1] in hash_pallas._pallas_usable  # per-width verdict cached
    # second call exercises the cached branch
    got2 = np.asarray(hash_pallas.fingerprint32_auto(mat, lens))
    assert (got2 == want).all()
    # a forced-False width must silently take the jnp path
    hash_pallas._pallas_usable[mat.shape[1]] = False
    got3 = np.asarray(hash_pallas.fingerprint32_auto(mat, lens))
    assert (got3 == want).all()
    del hash_pallas._pallas_usable[mat.shape[1]]


def test_device_hash_utf8_and_empty():
    strings = [b"", b"a", "key-éÅ".encode(), b"0123456789abcdef0123456789"]
    mat, lens = pack_strings(strings)
    got = np.asarray(fingerprint32_device(mat, lens))
    want = np.array([fingerprint32(s) for s in strings], dtype=np.uint32)
    assert (got == want).all()


def test_keyed_owner_lookup_matches_host_ring():
    from ringpop_tpu.hashring import HashRing

    servers = [f"10.0.0.{i}:3000" for i in range(24)]
    ring = HashRing()
    ring.add_remove_servers(servers, [])
    tokens, owners = build_ring_tokens(servers, 100)

    keys = [f"user:{i}:{i * 37}" for i in range(500)]
    mat, lens = pack_strings([k.encode() for k in keys])
    got = np.asarray(keyed_owner_lookup(tokens, owners, mat, lens))
    want = np.array([servers.index(ring.lookup(k)) for k in keys])
    assert (got == want).all()
