"""HashRing tests (model: reference hashring/hashring_test.go — distribution,
wraparound, checksum, batch add/remove — recast for the sorted-token-array
design)."""

import collections

import numpy as np
import pytest

from ringpop_tpu.events import RingChangedEvent, RingChecksumEvent, on
from ringpop_tpu.hashing import fingerprint32
from ringpop_tpu.hashring import HashRing


def servers(n, port=3000):
    return [f"10.0.0.{i}:{port}" for i in range(n)]


def test_empty_ring():
    r = HashRing()
    assert r.lookup("key") is None
    assert r.lookup_n("key", 3) == []
    assert r.servers() == []
    assert r.server_count() == 0


def test_add_remove_and_has():
    r = HashRing()
    assert r.add_server("a:1")
    assert not r.add_server("a:1")  # duplicate is a no-op
    assert r.has_server("a:1")
    assert r.remove_server("a:1")
    assert not r.remove_server("a:1")
    assert not r.has_server("a:1")


def test_checksum_matches_reference_formula():
    # hashring.go:102-120: farm32 of sorted addresses joined with ';'
    r = HashRing()
    r.add_remove_servers(["b:2", "a:1", "c:3"], [])
    assert r.checksum() == fingerprint32("a:1;b:2;c:3")


def test_checksum_changes_on_membership_change():
    r = HashRing()
    r.add_server("a:1")
    c1 = r.checksum()
    r.add_server("b:2")
    assert r.checksum() != c1


def test_lookup_deterministic_and_consistent():
    r = HashRing()
    r.add_remove_servers(servers(10), [])
    owner = r.lookup("some-key")
    assert owner in r.servers()
    for _ in range(5):
        assert r.lookup("some-key") == owner


def test_lookup_n_unique_and_wraparound():
    r = HashRing(replica_points=5)
    r.add_remove_servers(servers(8), [])
    got = r.lookup_n("k", 4)
    assert len(got) == len(set(got)) == 4
    # n >= server count returns all servers
    assert sorted(r.lookup_n("k", 50)) == sorted(r.servers())


def test_removal_only_remaps_owned_keys():
    # consistent-hashing property: removing a server must not move keys owned
    # by other servers
    r = HashRing()
    r.add_remove_servers(servers(10), [])
    keys = [f"key-{i}" for i in range(500)]
    before = {k: r.lookup(k) for k in keys}
    victim = "10.0.0.3:3000"
    r.remove_server(victim)
    for k, owner in before.items():
        if owner != victim:
            assert r.lookup(k) == owner


def test_distribution_across_servers():
    # parity check vs hashring_test.go distribution test
    r = HashRing()
    r.add_remove_servers(servers(10), [])
    counts = collections.Counter(r.lookup(f"key-{i}") for i in range(5000))
    assert len(counts) == 10
    for c in counts.values():
        assert 150 < c < 1200  # no pathological skew at 100 vnodes


def test_lookup_batch_matches_scalar():
    r = HashRing()
    r.add_remove_servers(servers(7), [])
    keys = [f"key-{i}" for i in range(300)]
    assert r.lookup_batch(keys) == [r.lookup(k) for k in keys]


def test_events_emitted():
    r = HashRing()
    changed, checks = [], []
    on(r.emitter, RingChangedEvent, changed.append)
    on(r.emitter, RingChecksumEvent, checks.append)
    r.add_remove_servers(["a:1", "b:2"], [])
    r.add_remove_servers([], ["a:1"])
    assert changed[0].servers_added == ["a:1", "b:2"]
    assert changed[1].servers_removed == ["a:1"]
    assert len(checks) == 2


def test_batch_add_remove_atomic():
    r = HashRing()
    r.add_server("a:1")
    assert r.add_remove_servers(["b:2"], ["a:1"])
    assert r.servers() == ["b:2"]
    # no-op when nothing changes
    assert not r.add_remove_servers(["b:2"], ["zz:9"])


def test_token_arrays_snapshot():
    r = HashRing(replica_points=10)
    r.add_remove_servers(servers(4), [])
    toks, owners, slist = r.token_arrays()
    assert toks.shape == owners.shape == (40,)
    assert list(toks) == sorted(toks)
    assert len(slist) == 4


def test_zero_replica_points_lookup_paths():
    """replica_points=0 leaves a server set with no tokens: every lookup
    flavor must return empty/None, not crash (regression: the n==1 bisect
    fast path indexed into the empty owner list)."""
    from ringpop_tpu.hashring import HashRing

    ring = HashRing(replica_points=0)
    ring.add_server("10.0.0.1:3000")
    assert ring.lookup("k") is None
    assert ring.lookup_n("k", 1) == []
    assert ring.lookup_n("k", 3) == []
    assert ring.lookup_n_batch(["k"], 2) == [[]]
    assert ring.lookup_batch(["k", "k2"]) == [None, None]


# -- incremental maintenance vs the rebuild oracle (serve-the-ring PR) -------


def _oracle_of(live, hashfunc=None, replica_points=10):
    oracle = HashRing(hashfunc=hashfunc, replica_points=replica_points)
    oracle.add_remove_servers(sorted(live), [])
    # force the FROM-SCRATCH argsort: the pin is incremental-vs-rebuild,
    # not incremental-vs-incremental-from-empty
    oracle._rebuild()
    oracle._compute_checksum()
    return oracle


def _assert_bit_identical(ring, oracle):
    assert np.array_equal(ring._tokens, oracle._tokens)
    assert np.array_equal(ring._owners, oracle._owners)
    assert np.array_equal(ring._tokens32, oracle._tokens32)
    assert np.array_equal(ring._owners32, oracle._owners32)
    assert ring._tokens_list == oracle._tokens_list
    assert ring._owners_list == oracle._owners_list
    assert ring._server_list == oracle._server_list
    assert ring.checksum() == oracle.checksum()


def test_incremental_matches_rebuild_random_churn():
    """The incremental add/remove path (merge-insert + mask + tie repair)
    must be BIT-identical to the from-scratch rebuild after every batch of
    a randomized churn sequence."""
    rng = np.random.default_rng(7)
    ring = HashRing(replica_points=10)
    pool = [f"10.1.{i // 256}.{i % 256}:3000" for i in range(160)]
    live: set[str] = set()
    for _ in range(40):
        free = [p for p in pool if p not in live]
        adds = list(rng.choice(free, size=min(len(free), int(rng.integers(0, 5))),
                               replace=False))
        rems = list(rng.choice(sorted(live),
                               size=min(len(live), int(rng.integers(0, 4))),
                               replace=False)) if live else []
        ring.add_remove_servers(adds, rems)
        live |= set(adds)
        live -= set(rems)
        _assert_bit_identical(ring, _oracle_of(live))


def test_incremental_matches_rebuild_collision_heavy():
    """A 97-value token space forces equal-token runs whose (token, owner)
    tie order the owner renumbering flips — the local re-sort repair must
    keep the arrays bit-identical to the rebuild."""

    def tiny(s):
        data = s if isinstance(s, bytes) else s.encode()
        return fingerprint32(data) % 97

    rng = np.random.default_rng(11)
    ring = HashRing(hashfunc=tiny, replica_points=5)
    pool = [f"s{i}:3000" for i in range(60)]
    live: set[str] = set()
    for _ in range(30):
        free = [p for p in pool if p not in live]
        adds = list(rng.choice(free, size=min(len(free), int(rng.integers(0, 4))),
                               replace=False))
        rems = list(rng.choice(sorted(live),
                               size=min(len(live), int(rng.integers(0, 3))),
                               replace=False)) if live else []
        ring.add_remove_servers(adds, rems)
        live |= set(adds)
        live -= set(rems)
        _assert_bit_identical(ring, _oracle_of(live, hashfunc=tiny,
                                               replica_points=5))


def test_incremental_drain_to_empty_and_refill():
    ring = HashRing(replica_points=10)
    srv = [f"a{i}:1" for i in range(8)]
    ring.add_remove_servers(srv, [])
    ring.add_remove_servers([], srv)  # drain through the incremental path
    assert ring._tokens.shape == (0,)
    assert ring._tokens_list == []
    ring.add_remove_servers(srv[:3], [])  # refill from empty
    _assert_bit_identical(ring, _oracle_of(set(srv[:3])))


def test_incremental_simultaneous_add_remove_renumbers():
    """One batch that adds a server sorting BEFORE the survivors and
    removes one sorting in the middle shifts every later owner id — the
    renumber LUT (not just the merge) is what keeps lookups right."""
    ring = HashRing(replica_points=10)
    ring.add_remove_servers(["m:1", "q:1", "t:1"], [])
    ring.add_remove_servers(["a:1", "z:1"], ["q:1"])
    _assert_bit_identical(ring, _oracle_of({"m:1", "t:1", "a:1", "z:1"}))
    assert ring.lookup("some-key") in {"a:1", "m:1", "t:1", "z:1"}
