"""Partition healing tests (model: reference swim/heal_partition_test.go —
partitions built by fiat, mock clocks advanced, heal asserted) and real-TCP
transport tests."""

import asyncio

import pytest

from ringpop_tpu.net import CallError, LocalNetwork, TCPChannel
from ringpop_tpu.swim.heal import attempt_heal, nodes_that_need_to_reincarnate
from ringpop_tpu.swim.member import ALIVE, FAULTY, SUSPECT, Change
from ringpop_tpu.swim.node import BootstrapOptions, Node, NodeOptions
from ringpop_tpu.util.clock import MockClock

from swim_utils import (
    bootstrap_nodes,
    converged,
    make_nodes,
    member_statuses,
    run,
    tick_all,
    wait_for_convergence,
)


def _partition_by_fiat(group_a, group_b):
    """Write Faulty states directly into memberlists, the reference trick
    (heal_partition_test.go:420-428 AddPartitionWithStatus)."""
    for node in group_a:
        for other in group_b:
            m = node.memberlist.member(other.address)
            node.memberlist.make_faulty(other.address, m.incarnation)
            node.disseminator.clear_change(other.address)
    for node in group_b:
        for other in group_a:
            m = node.memberlist.member(other.address)
            node.memberlist.make_faulty(other.address, m.incarnation)
            node.disseminator.clear_change(other.address)


def test_nodes_that_need_to_reincarnate():
    ma = [
        Change(address="a:1", incarnation=5, status=ALIVE),
        Change(address="b:2", incarnation=5, status=FAULTY),
    ]
    mb = [
        Change(address="a:1", incarnation=4, status=FAULTY),
        Change(address="b:2", incarnation=5, status=ALIVE),
    ]
    for_a, for_b = nodes_that_need_to_reincarnate(ma, mb)
    # b:2 is pingable in B but A's faulty@5 overrides B's alive@5 -> B must
    # hear a suspect to make b:2 reincarnate
    assert [c.address for c in for_b] == ["b:2"]
    # a:1 is pingable in A; B's view (faulty@4) does NOT override -> no-op
    assert for_a == []


def test_partition_heal_with_faulties():
    """Two halves declare each other faulty; attempt_heal reincarnates both
    sides via suspect rumors and later merges
    (model: TestPartitionHealWithFaulties heal_partition_test.go:15-53)."""

    async def main():
        network = LocalNetwork()
        nodes = make_nodes(4, network)
        await bootstrap_nodes(nodes)
        await wait_for_convergence(nodes)

        side_a, side_b = nodes[:2], nodes[2:]
        _partition_by_fiat(side_a, side_b)
        assert member_statuses(side_a[0])[side_b[0].address] == FAULTY
        assert member_statuses(side_b[0])[side_a[0].address] == FAULTY

        # heal attempts + gossip until both sides see everyone alive again
        for attempt in range(10):
            await attempt_heal(side_a[0], side_b[0].address)
            for _ in range(40):
                await tick_all(nodes)
                if converged(nodes):
                    break
            if all(
                s == ALIVE for n in nodes for s in member_statuses(n).values()
            ):
                break
        for n in nodes:
            assert all(s == ALIVE for s in member_statuses(n).values()), (
                n.address,
                member_statuses(n),
            )

    run(main())


def test_healer_heal_targets_faulty_and_unknown():
    async def main():
        network = LocalNetwork()
        nodes = make_nodes(3, network)
        await bootstrap_nodes(nodes)
        await wait_for_convergence(nodes)

        side_a, side_b = nodes[:1], nodes[1:]
        _partition_by_fiat(side_a, side_b)

        healed = await nodes[0].healer.heal()
        assert healed  # at least one heal attempt against the other side

        # heals may need several rounds: reincarnate first, merge later
        # (model: waitForPartitionHeal, heal_partition_test.go:473-519)
        for attempt in range(10):
            await nodes[0].healer.heal()
            for _ in range(40):
                await tick_all(nodes)
                if converged(nodes):
                    break
            if all(s == ALIVE for n in nodes for s in member_statuses(n).values()):
                break
        for n in nodes:
            assert all(s == ALIVE for s in member_statuses(n).values())

    run(main())


# ---------------------------------------------------------------------------
# Real TCP transport
# ---------------------------------------------------------------------------


def test_tcp_channel_basic_rpc():
    async def main():
        server = TCPChannel(app="t")
        await server.listen()

        async def echo(body, headers):
            return {"echo": body, "headers": headers}

        server.register("svc", "/echo", echo)
        client = TCPChannel(app="t")
        res = await client.call(
            server.hostport, "svc", "/echo", {"x": 1}, headers={"h": "v"}, timeout=2.0
        )
        assert res == {"echo": {"x": 1}, "headers": {"h": "v"}}

        # unknown endpoint -> remote error
        with pytest.raises(CallError, match="no handler"):
            await client.call(server.hostport, "svc", "/nope", {}, timeout=2.0)

        # connection refused -> CallError
        with pytest.raises(CallError, match="connect"):
            await client.call("127.0.0.1:1", "svc", "/echo", {}, timeout=2.0)

        await server.close()
        await client.close()

    run(main())


def test_tcp_two_node_swim_cluster():
    """End-to-end over real sockets: two nodes bootstrap and converge."""

    async def main():
        channels = [TCPChannel(app="tcp-test") for _ in range(2)]
        for ch in channels:
            await ch.listen()
        nodes = [
            Node("tcp-test", ch.hostport, ch, NodeOptions(clock=MockClock(1e6), seed=i))
            for i, ch in enumerate(channels)
        ]
        hosts = [n.address for n in nodes]

        async def boot(node):
            await node.bootstrap(BootstrapOptions(discover_provider=hosts, join_timeout=2.0))
            node.gossip.stop()
            node.healer.stop()

        await asyncio.gather(*(boot(n) for n in nodes))
        for _ in range(30):
            await tick_all(nodes)
            if converged(nodes):
                break
        assert converged(nodes)
        for n in nodes:
            assert n.member_count() == 2
        for ch in channels:
            await ch.close()

    run(main())
