"""Partition healing tests (model: reference swim/heal_partition_test.go —
partitions built by fiat, mock clocks advanced, heal asserted) and real-TCP
transport tests."""

import asyncio

import pytest

from ringpop_tpu.net import CallError, LocalNetwork, TCPChannel
from ringpop_tpu.swim.heal import attempt_heal, nodes_that_need_to_reincarnate
from ringpop_tpu.swim.member import ALIVE, FAULTY, SUSPECT, Change
from ringpop_tpu.swim.node import BootstrapOptions, Node, NodeOptions
from ringpop_tpu.util.clock import MockClock

from swim_utils import (
    bootstrap_nodes,
    converged,
    make_nodes,
    member_statuses,
    run,
    tick_all,
    wait_for_convergence,
)


def _partition_by_fiat(group_a, group_b):
    """Write Faulty states directly into memberlists, the reference trick
    (heal_partition_test.go:420-428 AddPartitionWithStatus)."""
    for node in group_a:
        for other in group_b:
            m = node.memberlist.member(other.address)
            node.memberlist.make_faulty(other.address, m.incarnation)
            node.disseminator.clear_change(other.address)
    for node in group_b:
        for other in group_a:
            m = node.memberlist.member(other.address)
            node.memberlist.make_faulty(other.address, m.incarnation)
            node.disseminator.clear_change(other.address)


def test_nodes_that_need_to_reincarnate():
    ma = [
        Change(address="a:1", incarnation=5, status=ALIVE),
        Change(address="b:2", incarnation=5, status=FAULTY),
    ]
    mb = [
        Change(address="a:1", incarnation=4, status=FAULTY),
        Change(address="b:2", incarnation=5, status=ALIVE),
    ]
    for_a, for_b = nodes_that_need_to_reincarnate(ma, mb)
    # b:2 is pingable in B but A's faulty@5 overrides B's alive@5 -> B must
    # hear a suspect to make b:2 reincarnate
    assert [c.address for c in for_b] == ["b:2"]
    # a:1 is pingable in A; B's view (faulty@4) does NOT override -> no-op
    assert for_a == []


def test_partition_heal_with_faulties():
    """Two halves declare each other faulty; attempt_heal reincarnates both
    sides via suspect rumors and later merges
    (model: TestPartitionHealWithFaulties heal_partition_test.go:15-53)."""

    async def main():
        network = LocalNetwork()
        nodes = make_nodes(4, network)
        await bootstrap_nodes(nodes)
        await wait_for_convergence(nodes)

        side_a, side_b = nodes[:2], nodes[2:]
        _partition_by_fiat(side_a, side_b)
        assert member_statuses(side_a[0])[side_b[0].address] == FAULTY
        assert member_statuses(side_b[0])[side_a[0].address] == FAULTY

        # heal attempts + gossip until both sides see everyone alive again
        for attempt in range(10):
            await attempt_heal(side_a[0], side_b[0].address)
            for _ in range(40):
                await tick_all(nodes)
                if converged(nodes):
                    break
            if all(
                s == ALIVE for n in nodes for s in member_statuses(n).values()
            ):
                break
        for n in nodes:
            assert all(s == ALIVE for s in member_statuses(n).values()), (
                n.address,
                member_statuses(n),
            )

    run(main())


def test_healer_heal_targets_faulty_and_unknown():
    async def main():
        network = LocalNetwork()
        nodes = make_nodes(3, network)
        await bootstrap_nodes(nodes)
        await wait_for_convergence(nodes)

        side_a, side_b = nodes[:1], nodes[1:]
        _partition_by_fiat(side_a, side_b)

        healed = await nodes[0].healer.heal()
        assert healed  # at least one heal attempt against the other side

        # heals may need several rounds: reincarnate first, merge later
        # (model: waitForPartitionHeal, heal_partition_test.go:473-519)
        for attempt in range(10):
            await nodes[0].healer.heal()
            for _ in range(40):
                await tick_all(nodes)
                if converged(nodes):
                    break
            if all(s == ALIVE for n in nodes for s in member_statuses(n).values()):
                break
        for n in nodes:
            assert all(s == ALIVE for s in member_statuses(n).values())

    run(main())


# ---------------------------------------------------------------------------
# Real TCP transport
# ---------------------------------------------------------------------------


def test_tcp_channel_basic_rpc():
    async def main():
        server = TCPChannel(app="t")
        await server.listen()

        async def echo(body, headers):
            return {"echo": body, "headers": headers}

        server.register("svc", "/echo", echo)
        client = TCPChannel(app="t")
        res = await client.call(
            server.hostport, "svc", "/echo", {"x": 1}, headers={"h": "v"}, timeout=2.0
        )
        assert res == {"echo": {"x": 1}, "headers": {"h": "v"}}

        # unknown endpoint -> remote error
        with pytest.raises(CallError, match="no handler"):
            await client.call(server.hostport, "svc", "/nope", {}, timeout=2.0)

        # connection refused -> CallError
        with pytest.raises(CallError, match="connect"):
            await client.call("127.0.0.1:1", "svc", "/echo", {}, timeout=2.0)

        await server.close()
        await client.close()

    run(main())


def test_tcp_two_node_swim_cluster():
    """End-to-end over real sockets: two nodes bootstrap and converge."""

    async def main():
        channels = [TCPChannel(app="tcp-test") for _ in range(2)]
        for ch in channels:
            await ch.listen()
        nodes = [
            Node("tcp-test", ch.hostport, ch, NodeOptions(clock=MockClock(1e6), seed=i))
            for i, ch in enumerate(channels)
        ]
        hosts = [n.address for n in nodes]

        async def boot(node):
            await node.bootstrap(BootstrapOptions(discover_provider=hosts, join_timeout=2.0))
            node.gossip.stop()
            node.healer.stop()

        await asyncio.gather(*(boot(n) for n in nodes))
        for _ in range(30):
            await tick_all(nodes)
            if converged(nodes):
                break
        assert converged(nodes)
        for n in nodes:
            assert n.member_count() == 2
        for ch in channels:
            await ch.close()

    run(main())


def test_tcp_msgpack_codec_rpc_and_interop():
    """msgpack frames work end-to-end, and mixed-codec peers interoperate
    (each side sends its codec; readers auto-detect per frame)."""

    async def main():
        server = TCPChannel(app="t", codec="msgpack")
        await server.listen()

        async def echo(body, headers):
            return {"echo": body, "headers": headers}

        server.register("svc", "/echo", echo)

        mp_client = TCPChannel(app="t", codec="msgpack")
        res = await mp_client.call(
            server.hostport, "svc", "/echo", {"x": 1, "s": "é", "b": [1, 2]},
            headers={"h": "v"}, timeout=2.0,
        )
        assert res == {"echo": {"x": 1, "s": "é", "b": [1, 2]}, "headers": {"h": "v"}}

        # json client -> msgpack server: request is a JSON line, response
        # comes back msgpack-framed; both ends auto-detect
        json_client = TCPChannel(app="t", codec="json")
        res = await json_client.call(
            server.hostport, "svc", "/echo", {"y": 2}, timeout=2.0
        )
        assert res["echo"] == {"y": 2}

        # msgpack client -> json server
        json_server = TCPChannel(app="t", codec="json")
        await json_server.listen()
        json_server.register("svc", "/echo", echo)
        res = await mp_client.call(json_server.hostport, "svc", "/echo", {"z": 3}, timeout=2.0)
        assert res["echo"] == {"z": 3}

        # remote handler errors still surface through msgpack framing
        with pytest.raises(CallError, match="no handler"):
            await mp_client.call(server.hostport, "svc", "/nope", {}, timeout=2.0)

        for ch in (server, json_server, mp_client, json_client):
            await ch.close()

    run(main())


def test_tcp_msgpack_swim_cluster_converges():
    """A SWIM cluster whose every channel speaks msgpack converges — the
    whole protocol payload schema round-trips through the binary codec."""

    async def main():
        channels = [TCPChannel(app="mp-test", codec="msgpack") for _ in range(3)]
        for ch in channels:
            await ch.listen()
        nodes = [
            Node("mp-test", ch.hostport, ch, NodeOptions(clock=MockClock(1e6), seed=i))
            for i, ch in enumerate(channels)
        ]
        hosts = [n.address for n in nodes]

        async def boot(node):
            await node.bootstrap(BootstrapOptions(discover_provider=hosts, join_timeout=2.0))
            node.gossip.stop()
            node.healer.stop()

        await asyncio.gather(*(boot(n) for n in nodes))
        for _ in range(8):
            for n in nodes:
                await n.gossip.protocol_period()
        assert len({n.memberlist.checksum() for n in nodes}) == 1
        for n in nodes:
            assert n.memberlist.count_reachable_members() == 3
        for n in nodes:
            n.destroy()
        for ch in channels:
            await ch.close()

    run(main())


def test_tcp_msgpack_unencodable_error_still_answers():
    """A handler error whose message carries surrogateescape bytes (the case
    JSON's ensure_ascii handles) must not hang a msgpack-codec caller: the
    server falls back to a JSON error frame instead of dropping the reply."""

    async def main():
        server = TCPChannel(app="t", codec="msgpack")
        await server.listen()

        async def bad(body, headers):
            raise OSError("bad path: " + b"caf\xe9".decode("utf-8", "surrogateescape"))

        server.register("svc", "/bad", bad)
        client = TCPChannel(app="t", codec="msgpack")
        with pytest.raises(CallError):
            await client.call(server.hostport, "svc", "/bad", {}, timeout=2.0)
        # and the connection survives for the next (well-formed) call
        server.register("svc", "/ok", lambda b, h: {"ok": True})
        res = await client.call(server.hostport, "svc", "/ok", {}, timeout=2.0)
        assert res == {"ok": True}
        await server.close()
        await client.close()

    run(main())


def test_tcp_reader_survives_garbage_frames():
    """Garbage bodies (scalar msgpack payloads, empty JSON objects) and
    malformed transport headers must not crash the reader: garbage breaks
    only its own connection, and '{}' gets a normal 'no handler' error
    reply.  r21: raw clients speak the fabric RPC framing — a 16-byte
    header (RPC tag | request id, blob count, body length) before each
    body; the body encodings themselves are the pre-fold bytes."""
    import struct

    from ringpop_tpu.net.channel import MAX_FRAME_BYTES
    from ringpop_tpu.parallel.fabric import _HDR, TAG_RPC_REQ, TAG_RPC_RES

    def req_frame(rid: int, body: bytes) -> bytes:
        return _HDR.pack(TAG_RPC_REQ | rid, 1, len(body)) + body

    async def dropped(r) -> bool:
        # a drop may surface as EOF or as RST (the server closes without
        # draining the bad payload); both mean "connection terminated,
        # nothing delivered" — what this test pins
        try:
            return await r.read(64) == b""
        except ConnectionError:
            return True

    async def main():
        server = TCPChannel(app="t")
        await server.listen()
        server.register("svc", "/ok", lambda b, h: {"ok": True})
        host, port = server.hostport.rsplit(":", 1)

        # msgpack body that unpacks to a scalar -> clean connection drop
        r, w = await asyncio.open_connection(host, int(port))
        w.write(req_frame(1, b"\xc1" + struct.pack(">I", 1) + b"\x05"))
        await w.drain()
        assert await dropped(r)  # server closed, no crash
        w.close()

        # transport header declaring an oversized body -> clean drop
        # BEFORE the server buffers anything
        r, w = await asyncio.open_connection(host, int(port))
        w.write(_HDR.pack(TAG_RPC_REQ | 2, 1, MAX_FRAME_BYTES + 1))
        await w.drain()
        w.write_eof()
        assert await dropped(r)
        w.close()

        # a non-RPC tag (an exchange-stream tag on the RPC port) is a
        # desynced peer -> clean drop
        r, w = await asyncio.open_connection(host, int(port))
        w.write(_HDR.pack(0x01000003, 1, 4) + b"ABCD")
        await w.drain()
        assert await dropped(r)
        w.close()

        # a bare '{}' JSON body is a real (malformed) request: it must get
        # an error REPLY, not be silently swallowed
        r, w = await asyncio.open_connection(host, int(port))
        w.write(req_frame(3, b"{}\n"))
        await w.drain()
        hdr = await asyncio.wait_for(r.readexactly(_HDR.size), timeout=2.0)
        tag, n_blobs, total = _HDR.unpack(hdr)
        assert tag == (TAG_RPC_RES | 3) and n_blobs == 1
        import json as _json

        res = _json.loads(await asyncio.wait_for(r.readexactly(total), timeout=2.0))
        assert res["ok"] is False and "no handler" in res["err"]
        w.close()

        # server still healthy for real clients
        client = TCPChannel(app="t")
        assert await client.call(server.hostport, "svc", "/ok", {}, timeout=2.0) == {"ok": True}
        await server.close()
        await client.close()

    run(main())


def test_tcp_oversized_frame_fails_fast_at_sender():
    """A frame over MAX_FRAME_BYTES raises at the SENDER with the actual
    cause (request -> CallError; response -> JSON error reply), instead of a
    silent receiver-side connection drop."""
    from ringpop_tpu.net.channel import MAX_FRAME_BYTES

    big = "x" * (MAX_FRAME_BYTES + 1024)

    async def main():
        server = TCPChannel(app="t")
        await server.listen()
        server.register("svc", "/big", lambda b, h: {"blob": big})
        client = TCPChannel(app="t")

        with pytest.raises(CallError, match="exceeds MAX_FRAME_BYTES"):
            await client.call(server.hostport, "svc", "/echo", {"blob": big}, timeout=5.0)

        with pytest.raises(CallError, match="response encode failed"):
            await client.call(server.hostport, "svc", "/big", {}, timeout=5.0)

        await server.close()
        await client.close()

    run(main())
