"""Plane-3 (host concurrency) lint tests.

Mirrors test_jaxlint.py's structure: every RPH rule has a trip/clean
fixture pair under tests/analysis_fixtures/<slug>/, the repo at HEAD is
clean (modulo the committed waivers), and the CLI exit codes hold.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import pytest

from ringpop_tpu.analysis import hostlint, waivers
from ringpop_tpu.analysis.findings import Finding

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_FIX = os.path.join(_REPO, "tests", "analysis_fixtures")
_JAXLINT = os.path.join(_REPO, "scripts", "jaxlint.py")
_DEFAULT_PATHS = ("ringpop_tpu", "scripts", "examples", "bench.py",
                  "__graft_entry__.py")

_SCHEMA = hostlint.load_schema_index(os.path.join(_REPO, "OBSERVABILITY.md"))

# rule -> expected (line, scope) list for the trip fixture.  Pinning
# lines keeps a refactor of the walker from silently shifting which
# statement gets blamed.
_TRIP_EXPECT = {
    "RPH301": [(14, "Pair.fwd")],
    "RPH302": [(15, "Box.slow"), (20, "Box.indirect")],
    "RPH303": [(7, "fire_and_forget")],
    "RPH304": [(17, "Counter._worker")],
    "RPH305": [(7, "emit"), (8, "emit")],
}


def _lint_fixture(slug: str, name: str):
    path = os.path.join(_FIX, slug, name + ".py")
    rel = os.path.relpath(path, _REPO)
    with open(path) as f:
        return hostlint.lint_source(f.read(), rel, _SCHEMA)


@pytest.mark.parametrize("rule", sorted(hostlint.RULES))
def test_rule_trips(rule):
    slug = hostlint.RULES[rule]
    findings = _lint_fixture(slug, "trip")
    assert findings, f"{slug}/trip.py produced no findings"
    assert {f.rule for f in findings} == {rule}
    assert [(f.line, f.scope) for f in findings] == _TRIP_EXPECT[rule]


@pytest.mark.parametrize("rule", sorted(hostlint.RULES))
def test_rule_clean(rule):
    slug = hostlint.RULES[rule]
    findings = _lint_fixture(slug, "clean")
    assert findings == [], [f.render() for f in findings]


def test_fixture_routing_isolates_rules():
    # A fixture directory is linted by exactly the rule whose slug it
    # carries: the thread-leak trip spawns a thread from a function, but
    # only RPH303 may fire there.
    assert hostlint._rule_applies("RPH303", "tests/analysis_fixtures/thread-leak/trip.py")
    assert not hostlint._rule_applies("RPH301", "tests/analysis_fixtures/thread-leak/trip.py")
    # outside fixtures: RPH305 is package-only, the rest cover scripts too
    assert hostlint._rule_applies("RPH305", "ringpop_tpu/cli/journal.py")
    assert not hostlint._rule_applies("RPH305", "scripts/gameday_smoke.py")
    assert hostlint._rule_applies("RPH302", "scripts/gameday_smoke.py")
    assert not hostlint._rule_applies("RPH302", "examples/demo.py")


def test_rph301_message_names_the_cycle():
    (f,) = _lint_fixture("lock-order-inversion", "trip")
    assert "Pair._a" in f.message and "Pair._b" in f.message
    assert "cycle" in f.message


def test_rph302_interprocedural_chain():
    # the L20 finding is purely interprocedural: indirect() holds the
    # lock and calls _push(), whose body does the sendall
    findings = _lint_fixture("blocking-under-lock", "trip")
    chain = [f for f in findings if f.line == 20]
    assert len(chain) == 1
    assert "_push()" in chain[0].message
    assert "sendall" in chain[0].message


# -- RPH305 schema index ------------------------------------------------------


def test_schema_index_loads_from_repo_doc():
    assert _SCHEMA is not None
    # spot-check kinds the package emits today
    for kind in ("header", "heal", "crash", "serve", "alert", "req", "res"):
        assert kind in _SCHEMA, kind
        assert "kind" in _SCHEMA[kind]
    assert "tick" in _SCHEMA["heal"]


def test_schema_index_missing_doc_or_section(tmp_path):
    assert hostlint.load_schema_index(str(tmp_path / "nope.md")) is None
    other = tmp_path / "plain.md"
    other.write_text("# Nothing here\n\n| a | b |\n|---|---|\n| x | `y` |\n")
    assert hostlint.load_schema_index(str(other)) is None


def test_rph305_with_custom_index_and_spread():
    src = (
        "def emit(j, extra):\n"
        "    j.write({'kind': 'heal', 'tick': 1})\n"
        "    j.write({'kind': 'heal', 'tick': 1, **extra})\n"
        "    j.write({'kind': 'mystery'})\n"
    )
    idx = {"heal": {"kind", "tick"}}
    findings = hostlint.lint_source(src, "ringpop_tpu/zz_fake.py", idx)
    rph305 = [f for f in findings if f.rule == "RPH305"]
    assert [f.line for f in rph305] == [4]
    assert "mystery" in rph305[0].message


def test_rph305_disabled_without_index():
    src = "def emit(j):\n    j.write({'kind': 'mystery'})\n"
    findings = hostlint.lint_source(src, "ringpop_tpu/zz_fake.py", None)
    assert [f for f in findings if f.rule == "RPH305"] == []


# -- waivers over RPH findings ------------------------------------------------


def test_waiver_matches_rph_scope(tmp_path):
    wpath = tmp_path / "w.toml"
    wpath.write_text(
        '[[waiver]]\n'
        'rule = "RPH302"\n'
        'path = "ringpop_tpu/parallel/fabric.py"\n'
        'scope = "RpcLink._send_loop"\n'
        'justification = "leaf lock whose purpose is wire-write serialization"\n'
    )
    wl = waivers.load_waivers(str(wpath))
    hit = Finding("RPH302", "ringpop_tpu/parallel/fabric.py", 10,
                  "RpcLink._send_loop", "blocking call sendmsg ...")
    miss = Finding("RPH302", "ringpop_tpu/parallel/fabric.py", 11,
                   "RpcLink._enqueue", "blocking call sendmsg ...")
    unused = waivers.apply_waivers([hit, miss], wl)
    assert hit.waived and not miss.waived
    assert unused == []


def test_repo_plane3_clean_at_head():
    findings = hostlint.lint_paths(list(_DEFAULT_PATHS), _REPO)
    wl = waivers.load_waivers(
        os.path.join(_REPO, "ringpop_tpu", "analysis", "waivers.toml"))
    waivers.apply_waivers(findings, wl)
    unwaived = [f for f in findings if not f.waived]
    assert unwaived == [], "\n".join(f.render() for f in unwaived)


# -- CLI ----------------------------------------------------------------------


def _run_cli(*argv, timeout=240):
    return subprocess.run(
        [sys.executable, _JAXLINT, *argv],
        capture_output=True, text=True, cwd=_REPO, timeout=timeout,
    )


def test_cli_plane3_trip_exits_1():
    p = _run_cli("--plane", "3",
                 "tests/analysis_fixtures/lock-order-inversion/trip.py")
    assert p.returncode == 1, p.stdout + p.stderr
    assert "RPH301" in p.stdout


def test_cli_plane3_clean_exits_0():
    p = _run_cli("--plane", "3",
                 "tests/analysis_fixtures/lock-order-inversion/clean.py")
    assert p.returncode == 0, p.stdout + p.stderr


def test_cli_plane3_repo_sweep_clean_and_json():
    p = _run_cli("--plane", "3", "--format", "json")
    assert p.returncode == 0, p.stdout + p.stderr
    doc = json.loads(p.stdout)
    assert doc["unwaived_count"] == 0
    assert doc["unused_waivers"] == []
    # the two fabric wire-write waivers show up as waived findings
    waived_rules = {f["rule"] for f in doc["findings"] if f["waived"]}
    assert "RPH302" in waived_rules
