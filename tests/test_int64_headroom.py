"""The r14 int32-headroom audit: every promoted/restructured form is
exercised at (or provably equivalent to) the N·K ≥ 2³¹ boundary.

The repo runs x64-disabled (RPA104), so there is no 64-bit traced-integer
escape hatch — the audit's fixes are structural: digest index lanes moved
to explicit wrapping-uint32 row/col form (``packbits.flat_index_u32``),
N·T-scaling telemetry reduces promoted to float32, coverage popcounts
chunked under uint32 with an int64 host fold.  Tier-1 proves the promoted
forms with a forced index-offset shim (small arrays whose GLOBAL offsets
sit just above 2³¹ and across the 2³² wrap); the slow-marked direct unit
digests a real > 2³¹-element plane.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ringpop_tpu.sim import telemetry
from ringpop_tpu.sim.packbits import flat_index_u32, mix32


def _old_leaf_sum(leaf, offset=0):
    """The pre-r14 flat-arange digest formula, in numpy uint64-mod-2^32
    arithmetic — the value contract the restructured form must keep."""
    v = np.asarray(leaf)
    if v.dtype == bool:
        v = v.astype(np.uint32)
    flat = v.reshape(-1).astype(np.uint64) & 0xFFFFFFFF
    idx = (np.uint64(offset) + np.arange(flat.size, dtype=np.uint64)) & np.uint64(
        0xFFFFFFFF
    )

    def np_mix(x):
        x = x.astype(np.uint32)
        with np.errstate(over="ignore"):
            x ^= x >> np.uint32(16)
            x = (x * np.uint32(0x85EB_CA6B)).astype(np.uint32)
            x ^= x >> np.uint32(13)
            x = (x * np.uint32(0xC2B2_AE35)).astype(np.uint32)
            x ^= x >> np.uint32(16)
        return x

    with np.errstate(over="ignore"):
        mixed = np_mix(flat.astype(np.uint32) ^ np_mix(idx.astype(np.uint32)))
        return int(mixed.astype(np.uint64).sum() & np.uint64(0xFFFFFFFF))


@pytest.mark.parametrize(
    "shape,dtype",
    [
        ((17, 5), np.int32),
        ((8, 3, 2), np.uint32),
        ((13,), np.int8),
        ((), np.int32),
        ((6, 32), bool),
    ],
)
def test_leaf_digest_sum_matches_flat_formula(shape, dtype):
    rng = np.random.default_rng(0)
    if dtype is bool:
        leaf = rng.random(shape) > 0.5
    else:
        leaf = rng.integers(0, np.iinfo(dtype).max, shape, dtype=dtype)
    assert int(telemetry.leaf_digest_sum(leaf)) == _old_leaf_sum(leaf)


@pytest.mark.parametrize(
    "offset",
    [
        0,
        2**31 - 8,  # lanes cross the int32 sign boundary
        2**31 + 3,  # entirely above int32
        2**32 - 8,  # lanes WRAP mod 2^32 mid-leaf
    ],
)
def test_leaf_digest_sum_offset_shim(offset):
    """The forced index-offset shim: a small leaf whose GLOBAL flat
    indices sit at the hazardous boundaries must digest exactly as the
    uint64-mod-2^32 reference — i.e. the promoted row/col form computes
    the same lanes an (impossible) overflow-free flat iota would."""
    rng = np.random.default_rng(1)
    leaf = rng.integers(0, 2**32, (4, 8), dtype=np.uint32)
    got = int(telemetry.leaf_digest_sum(leaf, offset=np.uint32(offset & 0xFFFFFFFF)))
    assert got == _old_leaf_sum(leaf, offset=offset)


def test_flat_index_u32_wraps_exactly():
    rows = jnp.asarray([0, 1, 2**20, 2**24 - 1], jnp.uint32)
    ncols = 256
    cols = jnp.asarray([0, 255, 7, 255], jnp.uint32)
    got = np.asarray(flat_index_u32(rows, ncols, cols)).astype(np.uint64)
    want = (
        np.asarray(rows).astype(np.uint64) * ncols + np.asarray(cols).astype(np.uint64)
    ) & np.uint64(0xFFFFFFFF)
    assert np.array_equal(got, want)
    # 2^24 * 256 == 2^32: the product wraps to exactly 0 — stated, not UB
    assert int(flat_index_u32(jnp.uint32(1 << 24), 256, jnp.uint32(0))) == 0


def test_digest_partials_compose_across_wrap_boundary():
    """Two blocks whose flat-index ranges straddle 2^32 still compose to
    the whole-plane digest — the multi-process digest certificate keeps
    working at 16M x 256 (where the SECOND half of the plane lives past
    the uint32 wrap)."""
    rng = np.random.default_rng(2)
    plane = rng.integers(0, 2**32, (8, 16), dtype=np.uint32)
    # pretend the plane's rows start at global row 2^28-2 of a K=16 plane:
    # flat offsets cross 2^32 inside block 2
    base_row = (1 << 28) - 2
    whole = _old_leaf_sum(plane, offset=(base_row * 16) & 0xFFFFFFFF)

    def part(rows, row0):
        return int(
            telemetry.leaf_digest_sum(
                rows, offset=np.uint32(((base_row + row0) * 16) & 0xFFFFFFFF)
            )
        )

    combined = (part(plane[:4], 0) + part(plane[4:], 4)) & 0xFFFFFFFF
    assert combined == whole


def test_fetch_counter_sums_survive_int32_overflow():
    """The N·T-scaling telemetry reduces: per-node int32 counters whose
    SUM exceeds 2³¹ must fetch as the (float32) count, not an int32 wrap
    to negative."""
    from ringpop_tpu.sim.delta import DeltaFaults
    from ringpop_tpu.sim.lifecycle import LifecycleParams, init_state

    params = LifecycleParams(n=8, k=32)
    tel = telemetry.zeros(params)
    big = np.full(8, 2**29, np.int32)  # sums to 2^32 > int32 max
    tel = tel._replace(
        pings=jnp.asarray(big),
        ping_reqs=jnp.asarray(big),
        probes_failed=jnp.asarray(big),
        incarnation_bumps=jnp.asarray(big),
        base_timer_fires=jnp.asarray(big),
    )
    rec, _ = telemetry.fetch(tel, init_state(params, seed=0), DeltaFaults())
    for key in ("ping_send", "ping_req_send", "ping_timeout", "refuted", "timer_fired"):
        v = float(rec[key])
        assert v == pytest.approx(2**32, rel=1e-6), (key, v)
        assert v > 0, f"{key} wrapped negative"


def test_coverage_chunks_stay_in_uint32():
    from ringpop_tpu.sim.delta_multihost import _k_coverage_bits

    plane = jnp.asarray(
        np.random.default_rng(3).integers(0, 2**32, (64, 4), dtype=np.uint32)
    )
    direct = int(
        np.asarray(jax.lax.population_count(plane)).astype(np.int64).sum()
    )
    for g in (1, 4, 16, 64):
        chunks = np.asarray(_k_coverage_bits(plane, g=g)).astype(np.int64)
        assert chunks.shape == (g,)
        assert int(chunks.sum()) == direct


@pytest.mark.slow
def test_direct_digest_above_2_31_elements():
    """The direct unit at N·K just above 2³¹: a real > 2³¹-element int8
    plane digests without a flat iota (the old form would need a
    2.1-billion-element arange) and bit-equal to the block-composed
    partials — exercising the promoted product where it actually
    overflows int32."""
    n, k = 2**16 + 8, 2**15  # (65544 * 32768) = 2^31 + 2^18 elements
    leaf = jnp.zeros((n, k), jnp.int8)  # content-free: the INDEX lanes are the test
    whole = int(telemetry.leaf_digest_sum(leaf))
    half = n // 2
    a = int(telemetry.leaf_digest_sum(leaf[:half], offset=np.uint32(0)))
    b = int(
        telemetry.leaf_digest_sum(
            leaf[half:], offset=np.uint32((half * k) & 0xFFFFFFFF)
        )
    )
    assert (a + b) & 0xFFFFFFFF == whole
