"""Tier-3 integration tests: real multi-process local clusters
(model: reference test/run-integration-tests cluster sizes 1..5 and 10;
the 10-process run is marked slow)."""

import asyncio
import signal

import pytest

from ringpop_tpu.harness import ProcessCluster

from swim_utils import run


@pytest.mark.parametrize("n", [2, 3])
def test_process_cluster_converges(n):
    async def main():
        cluster = ProcessCluster(n)
        cluster.start()
        try:
            stats = await cluster.wait_converged(expect_members=n, timeout=45)
            for s in stats.values():
                assert all(m["status"] == "alive" for m in s["membership"]["members"])
        finally:
            await cluster.shutdown()

    run(main())


@pytest.mark.slow
def test_ten_process_cluster_converges():
    """The reference's largest integration size (test/run-integration-tests:12)."""

    async def main():
        cluster = ProcessCluster(10)
        cluster.start()
        try:
            stats = await cluster.wait_converged(expect_members=10, timeout=90)
            assert len(stats) == 10
        finally:
            await cluster.shutdown()

    run(main())


def test_killed_process_is_detected_faulty():
    async def main():
        cluster = ProcessCluster(3, suspect_period=1.0)
        cluster.start()
        try:
            await cluster.wait_converged(expect_members=3, timeout=45)
            victim = cluster.hosts[2]
            survivors = cluster.hosts[:2]
            cluster.kill(victim, signal.SIGKILL)
            # ping timeout (1.5s) + ping-req + suspect period (1s)
            for obs in survivors:
                await cluster.wait_member_status(obs, victim, "faulty", timeout=45)
        finally:
            await cluster.shutdown()

    run(main())


def test_five_process_cluster_and_reap():
    async def main():
        cluster = ProcessCluster(5, suspect_period=1.0)
        cluster.start()
        try:
            await cluster.wait_converged(expect_members=5, timeout=60)
            victim = cluster.hosts[4]
            survivors = cluster.hosts[:4]
            cluster.kill(victim, signal.SIGKILL)
            await cluster.wait_member_status(survivors[0], victim, "faulty", timeout=45)

            # admin reap: faulty -> tombstone, gossiped cluster-wide
            client = await cluster.client()
            await client.call(survivors[0], "ringpop", "/admin/reap", {}, timeout=2.0)
            # tombstones are excluded from the checksum; survivors re-converge
            await cluster.wait_converged(hosts=survivors, timeout=45)
        finally:
            await cluster.shutdown()

    run(main())


def test_graceful_leave_and_rejoin_over_the_wire():
    """Tier-3 leave/rejoin scenario (reference it-tests; handlers
    swim/handlers.go:140-148): /admin/member/leave marks the node Leave
    cluster-wide; /admin/member/join reincarnates it back to alive."""

    async def main():
        cluster = ProcessCluster(3, suspect_period=1.0)
        cluster.start()
        try:
            await cluster.wait_converged(expect_members=3, timeout=45)
            leaver, observer = cluster.hosts[2], cluster.hosts[0]
            client = await cluster.client()

            await client.call(leaver, "ringpop", "/admin/member/leave", {}, timeout=2.0)
            await cluster.wait_member_status(observer, leaver, "leave", timeout=45)

            await client.call(leaver, "ringpop", "/admin/member/join", {}, timeout=2.0)
            await cluster.wait_member_status(observer, leaver, "alive", timeout=45)
            await cluster.wait_converged(timeout=45)
        finally:
            await cluster.shutdown()

    run(main())


def test_msgpack_wire_process_cluster():
    """A whole process cluster speaking the binary codec (testpop --wire
    msgpack) converges and serves admin RPCs — tier-3 coverage for the
    msgpack framing, including a json-codec client talking to it."""

    async def main():
        cluster = ProcessCluster(3, wire="msgpack")
        cluster.start()
        try:
            # the harness client speaks json; receivers auto-detect
            stats = await cluster.wait_converged(expect_members=3, timeout=45)
            for s in stats.values():
                assert all(m["status"] == "alive" for m in s["membership"]["members"])
        finally:
            await cluster.shutdown()

    run(main())
