"""jaxlint (ISSUE 4) — fixture-corpus coverage for every rule, waiver
semantics, and the repo-at-HEAD clean gate.

Each rule has a minimal tripping snippet and a clean snippet under
``tests/analysis_fixtures/<slug>/`` — the trip case MUST produce its
rule's finding and the clean case must produce none (the fixture-dir
scoping in ``astlint`` means only the directory's own rule applies, so a
clean fixture asserts zero findings of ANY rule).  Plane-2 fixtures
declare ``JAXLINT_TRACE_RULE`` + ``build()`` and run through
``trace_checks.check_fixture`` — the same dispatch ``scripts/jaxlint.py``
uses, so `make lint` pointed at a trip case provably exits non-zero.

The repo-at-HEAD tests are the real gate: plane 1 over the default sweep
and plane 2 over the nine public entry points (dense + 8-way virtual
mesh) must be clean modulo the justified waivers in
``analysis/waivers.toml`` — tier-1 fails the moment an engine edit
reintroduces a threefry bypass, a forbidden-phase collective, or a
structural sharded/unsharded divergence.
"""

from __future__ import annotations

import importlib.util
import json
import os
import subprocess
import sys

import pytest

from ringpop_tpu.analysis import astlint, hostlint, trace_checks, waivers
from ringpop_tpu.analysis.findings import Finding

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_FIX = os.path.join(_REPO, "tests", "analysis_fixtures")
_JAXLINT = os.path.join(_REPO, "scripts", "jaxlint.py")


def _lint_fixture(slug: str, name: str):
    rel = f"tests/analysis_fixtures/{slug}/{name}"
    return astlint.lint_source(open(os.path.join(_REPO, rel)).read(), rel)


def _load_fixture(slug: str, name: str):
    path = os.path.join(_FIX, slug, name)
    spec = importlib.util.spec_from_file_location(f"fx_{slug}_{name[:-3]}", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _run_trace_fixture(rule: str, name: str):
    mod = _load_fixture(trace_checks.TRACE_RULES[rule], name)
    assert mod.JAXLINT_TRACE_RULE == rule, "fixture declares the wrong rule"
    built = mod.build()
    fn, args = built[:-1], built[-1]
    if len(fn) == 1:
        fn = fn[0]
    return trace_checks.check_fixture(rule, fn, args)


# -- plane 1: one trip + one clean snippet per AST rule ----------------------


@pytest.mark.parametrize("rule", sorted(astlint.RULES))
def test_ast_rule_trips_on_fixture(rule):
    found = _lint_fixture(astlint.RULES[rule], "trip.py")
    assert any(f.rule == rule for f in found), (
        f"{rule} trip fixture produced no {rule} finding: "
        f"{[f.render() for f in found]}"
    )


@pytest.mark.parametrize("rule", sorted(astlint.RULES))
def test_ast_rule_clean_fixture_is_clean(rule):
    found = _lint_fixture(astlint.RULES[rule], "clean.py")
    assert not found, [f.render() for f in found]


def test_chaos_host_sync_fixture_pair():
    """The chaos-plane alias directory (astlint.FIXTURE_SLUG_ALIASES):
    a host-synced ``faults_at`` — int(tick) / np coercion of the
    schedule inside jit — must trip RPA103, and the pure elementwise
    shape (the real sim/chaos.py implementation) must be clean."""
    found = _lint_fixture("chaos-host-sync", "trip.py")
    assert any(f.rule == "RPA103" for f in found), [f.render() for f in found]
    assert {f.scope for f in found} == {"faults_at"}
    assert not _lint_fixture("chaos-host-sync", "clean.py")


def test_topo_host_sync_fixture_pair():
    """The topology-plane alias directory (astlint.FIXTURE_SLUG_ALIASES):
    a host-synced tier lookup — np coercion of the compiled id plane +
    ``.item()`` on the traced tier — must trip RPA103, and the pure
    elementwise blocked one-hot shape (the real ``delta.tier_pair_drop``
    implementation) must be clean."""
    found = _lint_fixture("topo-host-sync", "trip.py")
    assert any(f.rule == "RPA103" for f in found), [f.render() for f in found]
    assert {f.scope for f in found} == {"tier_pair_drop"}
    assert not _lint_fixture("topo-host-sync", "clean.py")


def test_host_sync_call_graph_closure():
    """RPA103 must flag host syncs in functions only REACHABLE from a jit
    root, not just directly decorated ones (the trip fixture's helper)."""
    found = _lint_fixture("host-sync-in-jit", "trip.py")
    scopes = {f.scope for f in found if f.rule == "RPA103"}
    assert "helper" in scopes, scopes
    assert "bad_norm" in scopes, scopes


# -- plane 2: one trip + one clean program per trace rule --------------------


@pytest.mark.parametrize("rule", sorted(trace_checks.TRACE_RULES))
def test_trace_rule_trips_on_fixture(rule):
    found = _run_trace_fixture(rule, "trip.py")
    assert any(f.rule == rule for f in found), (
        f"{rule} trip fixture produced no {rule} finding: "
        f"{[f.render() for f in found]}"
    )


@pytest.mark.parametrize("rule", sorted(trace_checks.TRACE_RULES))
def test_trace_rule_clean_fixture_is_clean(rule):
    found = _run_trace_fixture(rule, "clean.py")
    assert not found, [f.render() for f in found]


# -- waiver semantics --------------------------------------------------------


def test_waiver_requires_justification(tmp_path):
    p = tmp_path / "w.toml"
    p.write_text('[[waiver]]\nrule = "RPA101"\npath = "x.py"\nscope = "*"\n')
    with pytest.raises(waivers.WaiverError):
        waivers.load_waivers(str(p))


def test_waiver_rejects_unknown_syntax(tmp_path):
    p = tmp_path / "w.toml"
    p.write_text("[[waiver]]\nrule = [1, 2]\n")
    with pytest.raises(waivers.WaiverError):
        waivers.load_waivers(str(p))


def test_waiver_matching_and_unused_report(tmp_path):
    p = tmp_path / "w.toml"
    p.write_text(
        '[[waiver]]\nrule = "RPA101"\npath = "a.py"\nscope = "step"\n'
        'justification = "reasoned"\n'
        '[[waiver]]\nrule = "RPA102"\npath = "b.py"\nscope = "*"\n'
        'justification = "never matches"\n'
    )
    wl = waivers.load_waivers(str(p))
    fs = [
        Finding("RPA101", "a.py", 3, "step", "m"),
        Finding("RPA101", "a.py", 9, "step.<locals>.inner", "m"),
        Finding("RPA101", "other.py", 3, "step", "m"),
    ]
    unused = waivers.apply_waivers(fs, wl)
    assert fs[0].waived and fs[1].waived and not fs[2].waived
    assert fs[0].justification == "reasoned"
    assert [w["rule"] for w in unused] == ["RPA102"]


def test_checked_in_waivers_all_load_and_none_unused():
    """The committed waiver file parses, and every entry still matches a
    real finding at HEAD (stale waivers must be deleted, not hoarded)."""
    wl = waivers.load_waivers(
        os.path.join(_REPO, "ringpop_tpu", "analysis", "waivers.toml")
    )
    assert wl, "committed waiver file disappeared or parses empty"
    findings = astlint.lint_paths(list(_DEFAULT_PATHS), _REPO)
    findings += hostlint.lint_paths(list(_DEFAULT_PATHS), _REPO)
    unused = waivers.apply_waivers(findings, wl)
    assert not unused, [dict(w) for w in unused]


# -- repo at HEAD is clean ---------------------------------------------------

_DEFAULT_PATHS = ("ringpop_tpu", "scripts", "examples", "bench.py", "__graft_entry__.py")


def test_repo_plane1_clean_at_head():
    findings = astlint.lint_paths(list(_DEFAULT_PATHS), _REPO)
    wl = waivers.load_waivers(
        os.path.join(_REPO, "ringpop_tpu", "analysis", "waivers.toml")
    )
    waivers.apply_waivers(findings, wl)
    unwaived = [f for f in findings if not f.waived]
    assert not unwaived, "\n".join(f.render() for f in unwaived)


def test_repo_plane2_jaxpr_clean_at_head():
    """The nine entry points (incl. the chaos-enabled and r11 sequential-
    exchange steps), dense +
    sharded: no f64, no callbacks,
    confinement holds, donation aliases, sharded == unsharded modulo
    sharding ops — the acceptance bar of the jaxpr plane."""
    found = trace_checks.run_trace_checks()
    assert not found, "\n".join(f.render() for f in found)


def test_repo_plane2_hlo_confinement_clean_at_head():
    """Compiled sharded tick on the virtual mesh: no collective lands in
    a forbidden phase (peer-choice zero, nothing unattributed)."""
    found = trace_checks.run_hlo_checks()
    assert not found, "\n".join(f.render() for f in found)


def test_sharded_skeletons_are_nonvacuous():
    """The RPJ205 equivalence must compare real programs (hundreds of
    ops), and the comparator must actually see differences — guard
    against an excision set that silently swallows everything."""
    mesh = trace_checks._mesh8()
    dense = trace_checks.build_entrypoints(mesh=None)
    sharded = trace_checks.build_entrypoints(mesh=mesh)
    skel = trace_checks.trace_skeleton(dense["lifecycle_step"])
    assert len(skel) > 500, len(skel)
    assert trace_checks.check_structural_equivalence(
        "x", dense["lifecycle_step"], dense["delta_step"]
    ), "comparator failed to distinguish two different engines"
    colls = [
        (e.primitive.name, s)
        for e, s in trace_checks.iter_eqns(sharded["lifecycle_step"])
        if e.primitive.name in trace_checks.COLLECTIVE_PRIMS
    ]
    assert colls, "sharded trace shows no explicit collectives — mesh lost?"
    assert all("rumor-exchange" in s for _, s in colls), (
        "exchange collectives escaped their scope"
    )


# -- the CLI (what `make lint` runs) -----------------------------------------


def _run_cli(*argv, timeout=240):
    return subprocess.run(
        [sys.executable, _JAXLINT, *argv],
        capture_output=True, text=True, cwd=_REPO, timeout=timeout,
    )


def test_cli_trips_nonzero_on_ast_fixture():
    r = _run_cli("tests/analysis_fixtures/traced-roll/trip.py", "--plane", "1")
    assert r.returncode == 1, r.stdout + r.stderr
    assert "RPA102" in r.stdout


def test_cli_clean_fixture_exits_zero():
    r = _run_cli("tests/analysis_fixtures/traced-roll/clean.py", "--plane", "1")
    assert r.returncode == 0, r.stdout + r.stderr


def test_cli_json_listing_plane1():
    r = _run_cli("--plane", "1", "--format", "json")
    assert r.returncode == 0, r.stdout + r.stderr
    doc = json.loads(r.stdout)
    assert doc["unwaived_count"] == 0
    assert doc["waived_count"] >= 1  # the fullview threefry waivers
    assert doc["unused_waivers"] == []
    assert all(f["justification"] for f in doc["findings"] if f["waived"])


def test_cli_trips_nonzero_on_trace_fixture():
    """A plane-2 trip case through the real CLI: the fixture marker routes
    it to check_fixture and the process exits non-zero."""
    r = _run_cli(
        "tests/analysis_fixtures/donation-aliased/trip.py", timeout=300
    )
    assert r.returncode == 1, r.stdout + r.stderr
    assert "RPJ204" in r.stdout


# -- profile_mesh empty-dump hard failure (satellite) ------------------------


def _profile_mesh_module():
    spec = importlib.util.spec_from_file_location(
        "profile_mesh", os.path.join(_REPO, "scripts", "profile_mesh.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_profile_mesh_dies_on_missing_module(tmp_path):
    pm = _profile_mesh_module()
    with pytest.raises(SystemExit) as ei:
        pm._census_or_die(None, str(tmp_path), "step")
    assert ei.value.code == 4


def test_profile_mesh_dies_on_unparseable_dump(tmp_path):
    pm = _profile_mesh_module()
    bogus = tmp_path / "mod.after_optimizations.txt"
    bogus.write_text("this is not an HLO module\nat all\n")
    with pytest.raises(SystemExit) as ei:
        pm._census_or_die(str(bogus), str(tmp_path), "step")
    assert ei.value.code == 4


def test_profile_mesh_dies_on_collective_free_census(tmp_path):
    pm = _profile_mesh_module()
    plain = tmp_path / "mod.after_optimizations.txt"
    plain.write_text(
        "HloModule jit_f\n\nENTRY %main (p: f32[4]) -> f32[4] {\n"
        "  ROOT %add = f32[4] add(p, p)\n}\n"
    )
    with pytest.raises(SystemExit) as ei:
        pm._census_or_die(str(plain), str(tmp_path), "step")
    assert ei.value.code == 4
