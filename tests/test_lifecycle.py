"""Lifecycle engine: failure-detection dynamics at O(N·K).

Covers the SWIM lifecycle the reference implements per-node
(``swim/node.go:470-513``, ``state_transitions.go:90-117``,
``memberlist.go:337-354``) as emergent behavior of the vectorized engine:
crash → suspect → faulty, false suspicion → refutation, partition → heal,
eviction, and slot recycling under churn.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from ringpop_tpu.sim.delta import DeltaFaults
from ringpop_tpu.sim.lifecycle import (
    LifecycleParams,
    LifecycleSim,
    believed_status,
    detection_fraction,
    init_state,
    step,
)
from ringpop_tpu.swim.member import ALIVE, FAULTY, SUSPECT, TOMBSTONE


from tests.sim_faults import make_faults  # noqa: E402


def test_steady_state_quiet():
    """No faults → no rumors ever allocated; base stays all-alive."""
    sim = LifecycleSim(n=32, k=16, seed=0)
    sim.run(50)
    assert int((sim.state.r_subject >= 0).sum()) == 0
    assert bool((sim.state.base_status == ALIVE).all())
    assert bool(sim.state.base_present.all())


def test_crash_detected_and_becomes_faulty():
    """A crashed node is suspected, then declared faulty after the suspicion
    deadline, and every live node converges on that belief."""
    n = 64
    sim = LifecycleSim(n=n, k=32, seed=1, suspect_ticks=10)
    faults = make_faults(n, down=[7])
    ticks, ok = sim.run_until_detected([7], faults, min_status=FAULTY, max_ticks=600)
    assert ok, f"not detected after {ticks} ticks"
    # other nodes stay believed-alive everywhere
    others = believed_status(sim.state, [3, 19])
    assert bool((others == ALIVE).all())


def test_false_suspicion_refuted():
    """Suspicion of a LIVE node is refuted by reincarnation: the victim
    reasserts Alive at a higher incarnation and never turns faulty."""
    import functools

    import jax

    n = 48
    params = LifecycleParams(n=n, k=32, suspect_ticks=12)
    state = init_state(params, seed=2)
    jstep = jax.jit(functools.partial(step, params))  # 68 eager ticks cost ~19 s
    # drop every message for a while: probes fail, suspects pile up,
    # but ping-reqs also fail -> inconclusive, no declarations. Instead,
    # partition node 5 away briefly so it gets suspected, then heal.
    group = np.zeros(n, np.int32)
    group[5] = 1
    part = DeltaFaults(up=jnp.ones(n, bool), group=jnp.asarray(group))
    heal = DeltaFaults(up=jnp.ones(n, bool))
    for _ in range(8):
        state = jstep(state, part)
    # under partition some nodes should have declared node 5 suspect
    sus = believed_status(state, [5])
    assert int((sus == SUSPECT).sum()) > 0
    # heal before the suspicion deadline can finish propagating faulty
    for _ in range(60):
        state = jstep(state, heal)
    final = believed_status(state, [5])
    assert bool((final == ALIVE).all()), np.asarray(final).tolist()
    # refutation bumped the victim's incarnation
    assert int(state.self_inc[5]) > 0


def test_faulty_to_tombstone_to_evict():
    """The faulty→tombstone→evict chain runs on deadline arrays (reference
    state_transitions.go:90-117 + memberlist.Evict)."""
    n = 32
    sim = LifecycleSim(
        n=n, k=32, seed=3, suspect_ticks=5, faulty_ticks=10, tombstone_ticks=10
    )
    faults = make_faults(n, down=[4])
    # long enough for suspect(5) + faulty(10) + tombstone(10) + dissemination
    for _ in range(40):
        sim.tick(faults)
    ticks, ok = sim.run_until_detected([4], faults, min_status=TOMBSTONE, max_ticks=800)
    assert ok
    # eventually evicted from the base entirely
    for _ in range(400):
        sim.tick(faults)
        if not bool(sim.state.base_present[4]):
            break
    assert not bool(sim.state.base_present[4])


def test_partition_detection_and_heal():
    """30%/70% partition: each side declares the other faulty; healing the
    partition lets refutations re-establish a fully-alive view."""
    n = 40
    sim = LifecycleSim(n=n, k=96, seed=4, suspect_ticks=8, alloc_per_tick=96)
    group = np.zeros(n, np.int32)
    group[: int(0.3 * n)] = 1
    part = DeltaFaults(up=jnp.ones(n, bool), group=jnp.asarray(group))
    for _ in range(120):
        sim.tick(part)
    # majority side believes minority faulty
    minority = list(range(int(0.3 * n)))
    frac = detection_fraction(sim.state, minority, part, min_status=FAULTY)
    assert float(frac.mean()) > 0.5
    # heal: everyone reconverges to alive within a few hundred ticks
    heal = DeltaFaults(up=jnp.ones(n, bool))
    ok = False
    for _ in range(40):
        for _ in range(10):
            sim.tick(heal)
        status = believed_status(sim.state, list(range(n)))
        if bool((status == ALIVE).all()):
            ok = True
            break
    assert ok, "views did not reconverge to all-alive after heal"


def test_slot_recycling_under_sequential_churn():
    """K slots far below total event count: folding must recycle slots."""
    n = 48
    sim = LifecycleSim(n=n, k=16, seed=5, suspect_ticks=4, faulty_ticks=100000)
    down = []
    for victim in (3, 9, 21, 33):
        down.append(victim)
        faults = make_faults(n, down=down)
        ticks, ok = sim.run_until_detected(
            down, faults, min_status=FAULTY, max_ticks=900
        )
        assert ok, f"victim {victim} undetected (slots leaked?)"
    # all four victims faulty, slots mostly reclaimed
    assert int((sim.state.r_subject >= 0).sum()) <= 16


def test_slot_saturation_retries_transitions():
    """K far too small for the concurrent failures: fired suspicion timers
    must retry until their successor rumor finds a slot (regression: a
    fired-but-unplaced transition used to be dropped forever)."""
    n = 24
    sim = LifecycleSim(n=n, k=2, seed=11, suspect_ticks=4, alloc_per_tick=2)
    victims = [1, 2, 3]
    faults = make_faults(n, down=victims)
    ticks, ok = sim.run_until_detected(victims, faults, min_status=FAULTY, max_ticks=2000)
    assert ok, f"saturated slots dropped a transition (after {ticks} ticks)"


def test_packet_loss_still_converges():
    """BASELINE config: 5% packet loss — detection still completes and no
    live node ends up believed-faulty."""
    n = 64
    sim = LifecycleSim(n=n, k=64, seed=6, suspect_ticks=10, alloc_per_tick=64)
    faults = make_faults(n, down=[11], drop=0.05)
    ticks, ok = sim.run_until_detected([11], faults, min_status=FAULTY, max_ticks=1500)
    assert ok
    # spurious suspicions from drops must have been refuted by now
    sim.run(150, make_faults(n, down=[11], drop=0.0))
    status = believed_status(sim.state, [0, 1, 2, 30, 63])
    assert bool((status == ALIVE).all())


def test_detection_fraction_large_path_matches_small():
    """The slot-walk large-scale detection_fraction must agree exactly with
    the vectorized O(N·K·S) path on rich mixed states: suspects in flight,
    fired faulty transitions, folded bases, drop-induced refutations."""
    from ringpop_tpu.sim.lifecycle import _detection_fraction_large, detection_fraction

    n = 96
    sim = LifecycleSim(n=n, k=24, seed=21, suspect_ticks=6, alloc_per_tick=8)
    victims = [5, 40, 41, 77]
    faults = make_faults(n, down=victims, drop=0.08)
    subjects = victims + [0, 17, 60]  # dead + live subjects
    for ticks in (4, 8, 12, 20, 40, 80, 160):
        sim.run(4 if ticks <= 20 else ticks // 4, faults)
        for min_status in (SUSPECT, FAULTY, TOMBSTONE):
            small = np.asarray(detection_fraction(sim.state, subjects, faults, min_status))
            large = np.asarray(
                _detection_fraction_large(sim.state, subjects, faults, min_status)
            )
            assert np.allclose(small, large), (ticks, min_status, small, large)


def test_detection_complete_matches_fraction():
    """The on-device boolean check (the one run_until_detected jits into its
    while_loop) must agree with ``(detection_fraction >= 1).all()`` on the
    same rich mixed states the large-path test uses — including the
    all-detected end state and base-only (no-slot) subjects."""
    import functools

    import jax

    from ringpop_tpu.sim.lifecycle import detection_complete, detection_fraction

    n = 96
    sim = LifecycleSim(n=n, k=24, seed=33, suspect_ticks=6, alloc_per_tick=8)
    victims = [5, 40, 41, 77]
    faults = make_faults(n, down=victims, drop=0.08)
    subject_sets = ([5], victims, victims + [0, 17, 60])
    # jit both sides per (shape, min_status) combo: 360 eager evaluations
    # of these queries cost ~100 s of pure dispatch on one core
    jc = jax.jit(
        functools.partial(detection_complete), static_argnames="min_status"
    )
    jf = jax.jit(
        functools.partial(detection_fraction), static_argnames="min_status"
    )
    checked_true = 0
    for _ in range(40):
        sim.run(8, faults)
        for subjects in subject_sets:
            subj = jnp.asarray(subjects, jnp.int32)
            for min_status in (SUSPECT, FAULTY, TOMBSTONE):
                frac = np.asarray(jf(sim.state, subj, faults, min_status=min_status))
                want = bool((frac >= 1.0).all())
                got = bool(jc(sim.state, subj, faults, min_status=min_status))
                assert got == want, (subjects, min_status, frac)
                checked_true += want
    assert checked_true > 0, "never reached a detected state — test too weak"


def test_view_checksums_match_bruteforce_and_converge():
    """The O(N·K) slot-walk view checksum must equal the brute-force
    believed_key-based sum at every state, diverge across nodes while
    rumors are in flight, and agree across live nodes at quiescence —
    the reference's all-checksums-agree convergence criterion
    (swim/test_utils.go:164-199)."""
    import jax.numpy as jnp

    from ringpop_tpu.sim.lifecycle import (
        TOMBSTONE as TS,
        _mix32,
        _status_of,
        believed_key,
        checksums_converged,
        view_checksums,
    )

    def brute(state):
        n = state.learned.shape[0]
        bk = believed_key(state, list(range(n)))  # [N, S=N]
        include = (bk >= 0) & (_status_of(jnp.maximum(bk, 0)) != TS)
        subj = jnp.arange(n, dtype=jnp.uint32)[None, :]
        h = _mix32(_mix32(subj) ^ bk.astype(jnp.uint32))
        return np.asarray(jnp.where(include, h, jnp.uint32(0)).sum(axis=1, dtype=jnp.uint32))

    n = 72
    victims = [5, 40, 41]
    faults = make_faults(n, down=victims, drop=0.05)
    sim = LifecycleSim(n=n, k=20, seed=7, suspect_ticks=5, alloc_per_tick=8)
    saw_divergence = False
    for _ in range(30):
        sim.run(6, faults)
        got = np.asarray(view_checksums(sim.state, faults))
        np.testing.assert_array_equal(got, brute(sim.state))
        live = np.asarray(faults.up)
        saw_divergence |= len(np.unique(got[live])) > 1
    assert saw_divergence, "checksums never diverged mid-protocol — test too weak"
    # run to quiescence: all victims detected and rumors folded
    sim.run_until_detected(victims, faults, max_ticks=2000, check_every=16)
    for _ in range(60):
        sim.run(8, faults)
        if bool(checksums_converged(sim.state, faults)):
            break
    assert bool(checksums_converged(sim.state, faults))
    got = np.asarray(view_checksums(sim.state, faults))
    live = np.asarray(faults.up)
    assert len(np.unique(got[live])) == 1


def test_run_until_converged_quiescence():
    """The checksum-convergence runner: 0 ticks on an already-quiescent
    cluster; after a crash it runs until every live view agrees (which
    implies the victim was detected and the rumors folded)."""
    from ringpop_tpu.sim.lifecycle import detection_complete

    sim = LifecycleSim(n=48, k=12, seed=2, suspect_ticks=5)
    ticks, ok = sim.run_until_converged()
    assert ok and ticks == 0

    # crash a node and let the protocol notice (a suspicion allocates);
    # then convergence = rumors drained + all live views agree, which for a
    # dead victim implies detection happened along the way (the reference's
    # tests likewise act first, then waitForConvergence)
    faults = make_faults(48, down=[9])
    warm = 0
    while not bool((np.asarray(sim.state.r_subject) >= 0).any()):
        sim.run(2, faults)
        warm += 2
        assert warm < 100, "no suspicion ever allocated"
    # zero budget: the check runs but the sim must not advance
    t_before = int(sim.state.tick)
    zticks, zok = sim.run_until_converged(faults, max_ticks=0)
    assert zticks == 0 and not zok and int(sim.state.tick) == t_before

    ticks, ok = sim.run_until_converged(faults, max_ticks=2000, check_every=8)
    assert ok and ticks > 0
    assert not (np.asarray(sim.state.r_subject) >= 0).any()
    # quiescence may legitimately land while the victim is still only
    # Suspect in every view (faulty timer pending on the base); full
    # detection still follows
    dticks, dok = sim.run_until_detected([9], faults, max_ticks=2000, check_every=8)
    assert dok
    assert bool(detection_complete(sim.state, [9], faults))
    # already-detected: the entry check answers truthfully without
    # stepping, even on a zero budget
    t_before = int(sim.state.tick)
    again = sim.run_until_detected([9], faults, max_ticks=0, check_every=8)
    assert again == (0, True) and int(sim.state.tick) == t_before


def test_detection_complete_no_live_observers_is_false():
    """With zero live observers the fraction is 0/1 per subject, so the
    on-device check must report incomplete — a cluster with nobody left to
    observe never 'detects' anything."""
    from ringpop_tpu.sim.lifecycle import detection_complete

    n = 16
    sim = LifecycleSim(n=n, k=8, seed=1, suspect_ticks=4)
    everyone = make_faults(n, down=list(range(n)))
    sim.run(4, everyone)
    assert not bool(detection_complete(sim.state, [3], everyone))


def test_run_until_detected_device_loop_matches_host_check():
    """The jitted while_loop runner must stop at the same (check_every-
    granular) tick the per-block host check would."""
    n = 64
    faults = make_faults(n, down=[7])
    a = LifecycleSim(n=n, k=16, seed=3, suspect_ticks=5)
    ticks_dev, ok_dev = a.run_until_detected(
        [7], faults, max_ticks=600, check_every=8, blocks_per_dispatch=4
    )
    from ringpop_tpu.sim.lifecycle import detection_complete

    b = LifecycleSim(n=n, k=16, seed=3, suspect_ticks=5)
    ticks_host = 0
    ok_host = False
    while ticks_host < 600:
        b.run(8, faults)
        ticks_host += 8
        if bool(detection_complete(b.state, [7], faults)):
            ok_host = True
            break
    assert ok_dev and ok_host
    assert ticks_dev == ticks_host


def test_crashed_node_revives_and_recovers():
    """Elastic recovery (SURVEY §5): a node detected faulty comes back up,
    learns it is believed faulty from the first exchange that reaches it,
    refutes at a higher incarnation, and the whole cluster returns to an
    all-alive view (reference: options.go:256-269 — faulty members rejoin
    and resume their ring position)."""
    n = 48
    sim = LifecycleSim(n=n, k=64, seed=13, suspect_ticks=6)
    dead = make_faults(n, down=[20])
    ticks, ok = sim.run_until_detected([20], dead, min_status=FAULTY, max_ticks=800)
    assert ok
    # revive: node 20 resumes probing; detection of its own detraction
    # triggers refutation-by-reincarnation
    alive = make_faults(n)
    recovered = False
    for _ in range(60):
        sim.run(10, alive)
        status = believed_status(sim.state, list(range(n)))
        if bool((status == ALIVE).all()):
            recovered = True
            break
    assert recovered, "revived node did not re-establish an all-alive view"
    assert int(sim.state.self_inc[20]) > 0  # reincarnated


def test_evicted_node_readmitted_via_join():
    """Elastic growth: after the full suspect→faulty→tombstone→evict chain
    removes a member, admit() re-introduces it via an Alive rumor that
    gossips out and folds back into the base (join-path analog)."""
    from ringpop_tpu.sim.lifecycle import admit

    n = 32
    sim = LifecycleSim(
        n=n, k=32, seed=17, suspect_ticks=4, faulty_ticks=6, tombstone_ticks=6
    )
    faults = make_faults(n, down=[9])
    evicted = False
    for _ in range(200):
        sim.tick(faults)
        if not bool(sim.state.base_present[9]):
            evicted = True
            break
    assert evicted, "node 9 was never evicted"

    # node 9 restarts and rejoins
    sim.state = admit(sim.params, sim.state, 9)
    alive = make_faults(n)
    back = False
    for _ in range(40):
        sim.run(10, alive)
        status = believed_status(sim.state, [9])
        if bool((status == ALIVE).all()) and bool(sim.state.base_present[9]):
            back = True
            break
    assert back, "re-admitted node did not rejoin the converged base"


def test_jit_shapes_stable_and_sharded():
    """The step runs under jit with in/out shardings on the 8-device CPU
    mesh (node × rumor), proving the multi-chip path compiles + executes."""
    import jax
    from jax.sharding import Mesh

    from ringpop_tpu.sim.lifecycle import state_shardings

    devs = np.asarray(jax.devices("cpu")[:8]).reshape(4, 2)
    mesh = Mesh(devs, ("node", "rumor"))
    # k=64 -> learned is uint32[N, 2] words: the 2-way rumor axis shards
    # one word per device (the packed plane's rumor axis is words, so K
    # must supply >= 32 slots per rumor shard)
    params = LifecycleParams(n=64, k=64, suspect_ticks=6)
    state = init_state(params, seed=7)
    state = jax.tree.map(jax.device_put, state, state_shardings(mesh, k=params.k))
    faults = make_faults(64, down=[9])
    stepper = jax.jit(lambda s: step(params, s, faults))
    for _ in range(30):
        state = stepper(state)
    assert int(state.tick) == 30
    frac = detection_fraction(state, [9], faults, min_status=SUSPECT)
    assert float(frac[0]) >= 0.0  # executes end-to-end under sharding


@pytest.mark.slow
def test_scale_spot_check_20k():
    """100k-class config scaled for CI: 20k nodes, crash 5, detect all."""
    n = 20_000
    sim = LifecycleSim(n=n, k=128, seed=8, suspect_ticks=15)
    victims = [17, 999, 5000, 12345, 19999]
    faults = make_faults(n, down=victims)
    ticks, ok = sim.run_until_detected(victims, faults, min_status=FAULTY, max_ticks=1200)
    assert ok, f"only partial detection after {ticks} ticks"


def test_sparse_topk_paths_bit_identical(monkeypatch):
    """The hierarchical candidate selection (per-block compress + select +
    cross-block merge, lax.cond overflow fallback) must be BIT-identical
    to the dense ``lax.top_k`` it replaces — including scatter side
    effects downstream of padding entries and stable tie order at the m
    boundary (simultaneous declarations carry equal keys, so which
    subjects win slots is order-sensitive).

    Caps are monkeypatched so a 512-node run exercises every branch:
    dense (n <= min_n), hierarchical (per-block candidates <= cap), and
    overflow (cap below any block's candidate count -> cond falls back
    to the full sort).
    """
    from ringpop_tpu.sim import lifecycle

    from ringpop_tpu.sim.packbits import block_count

    n, k = 512, 16
    # two fault layouts: SPREAD (~3 victims per 32-subject block at the
    # default 16 blocks — tie-heavy cross-block merges) and PACKED (30
    # victims inside ONE block — more concurrent candidates than any
    # cap >= m can hold, which is the only way to reach the runtime
    # overflow cond: the static ``m > cap`` guard already eats cap < m)
    spread = list(range(3, 503, 10))
    packed = list(range(30)) + [100, 300]
    params = LifecycleParams(n=n, k=k, alloc_per_tick=8, suspect_ticks=4)

    # record which runtime branch each eager _top_m_sparse call could
    # take, so the coverage claims below cannot rot into vacuity again
    # (regression: a cap=1 'overflow' run was statically dense via the
    # m > cap guard and compared dense against dense)
    saw = {"hier": False, "overflow": False}
    orig_top_m = lifecycle._top_m_sparse

    def recording_top_m(cand, m):
        cap = lifecycle._SPARSE_TOPK_CAP
        if n > max(cap, lifecycle._SPARSE_TOPK_MIN_N) and m <= cap:
            b = block_count(n, lifecycle._TOPK_BLOCKS)
            counts = (np.asarray(cand).reshape(b, n // b) >= 0).sum(axis=1)
            cap_eff = min(cap, n // b)
            if (counts > cap_eff).any():
                saw["overflow"] = True
            elif counts.sum():
                saw["hier"] = True
        return orig_top_m(cand, m)

    monkeypatch.setattr(lifecycle, "_top_m_sparse", recording_top_m)

    def run(cap, victims, min_n=0):
        monkeypatch.setattr(lifecycle, "_SPARSE_TOPK_CAP", cap)
        monkeypatch.setattr(lifecycle, "_SPARSE_TOPK_MIN_N", min_n)
        faults = make_faults(n, down=victims)
        state = init_state(params, seed=3)
        out = []
        for _ in range(30):
            state = step(params, state, faults)  # eager: recorder sees values
            out.append(state)
        return out

    dense_spread = run(4096, spread, min_n=1 << 30)  # full top_k, statically
    hier = run(32, spread)  # every block's candidates (~3) <= cap
    assert saw["hier"], "hierarchical branch never engaged — coverage rotted"
    dense_packed = run(4096, packed, min_n=1 << 30)
    saw["overflow"] = False
    overflow = run(8, packed)  # block 0 exceeds cap -> cond -> full sort
    assert saw["overflow"], "overflow cond never engaged — coverage rotted"

    for oracle, variant, tag in (
        (dense_spread, hier, "hierarchical"),
        (dense_packed, overflow, "overflow"),
    ):
        for t, (sa, sb) in enumerate(zip(oracle, variant)):
            for f, va, vb in zip(sa._fields, sa, sb):
                assert np.array_equal(np.asarray(va), np.asarray(vb)), (
                    f"{tag} diverges from dense at tick {t} field {f}"
                )


def test_hierarchical_topk_sharded_bit_identical(monkeypatch):
    """r6 satellite: the hierarchical select must stay bit-identical to
    the dense oracle UNDER THE 4×2 DEVICE MESH — the per-node-shard local
    select, the cross-shard merge (tie-heavy: simultaneous suspicions
    carry equal keys, so the merge's (block asc, index asc) tie order is
    load-bearing), and the overflow fallback all execute against sharded
    operands, where a partitioner-introduced reorder would be invisible
    to the unsharded tests above."""
    import functools

    import jax
    from jax.sharding import Mesh

    from ringpop_tpu.sim import lifecycle
    from ringpop_tpu.sim.lifecycle import state_shardings

    n, k = 512, 64  # k = 32 words × 2 rumor shards
    # same two layouts as the unsharded test above: spread exercises the
    # cross-block merge ties, packed (30 victims in block 0) pushes one
    # block past any cap >= m so the runtime overflow cond actually runs
    # (the eager test above ASSERTS these layouts reach those branches;
    # here the runs are jitted, so the layouts carry the coverage)
    spread = list(range(3, 503, 10))
    packed = list(range(30)) + [100, 300]
    params = LifecycleParams(n=n, k=k, alloc_per_tick=8, suspect_ticks=4)
    devs = np.asarray(jax.devices("cpu")[:8]).reshape(4, 2)
    mesh = Mesh(devs, ("node", "rumor"))

    def run(cap, victims, min_n=0, sharded=True):
        monkeypatch.setattr(lifecycle, "_SPARSE_TOPK_CAP", cap)
        monkeypatch.setattr(lifecycle, "_SPARSE_TOPK_MIN_N", min_n)
        faults = make_faults(n, down=victims)
        state = init_state(params, seed=3)
        if sharded:
            state = jax.tree.map(
                jax.device_put, state, state_shardings(mesh, k=params.k)
            )
        jstep = jax.jit(functools.partial(step, params))
        out = []
        for _ in range(24):
            state = jstep(state, faults)
            out.append(jax.tree.map(np.asarray, state))
        return out

    oracle_spread = run(4096, spread, min_n=1 << 30, sharded=False)
    oracle_packed = run(4096, packed, min_n=1 << 30, sharded=False)
    cases = (
        ("sharded-dense", oracle_spread, run(4096, spread, min_n=1 << 30)),
        ("sharded-hier", oracle_spread, run(32, spread)),  # local select+merge
        ("sharded-overflow", oracle_packed, run(8, packed)),  # cond full sort
    )
    for tag, oracle, variant in cases:
        for t, (sa, sb) in enumerate(zip(oracle, variant)):
            for f, va, vb in zip(sa._fields, sa, sb):
                assert np.array_equal(va, vb), (
                    f"{tag} diverges from the dense oracle at tick {t} field {f}"
                )


def test_sparse_topk_branches_pinned(monkeypatch):
    """Unit-level pin of WHICH _top_m_sparse branch runs: the step-level
    tests can't observe branch selection, so a drift in candidate counts
    could silently turn the 'hierarchical' coverage into overflow-fallback
    coverage.  Per-BLOCK candidate counts (the cap is per node block
    since the r6 hierarchical rewrite) are constructed by hand on both
    sides of the cap, including cross-block boundary ties, an empty
    candidate set, and count == cap exactly."""
    import jax

    from ringpop_tpu.sim import lifecycle

    monkeypatch.setattr(lifecycle, "_SPARSE_TOPK_MIN_N", 0)
    monkeypatch.setattr(lifecycle, "_SPARSE_TOPK_CAP", 4)
    n, m = 512, 4  # 16 blocks of 32 subjects; per-block cap 4
    rng = np.random.default_rng(7)

    def check(per_block, tag, vals=None):
        """per_block: candidate count to place in each 32-subject block."""
        cand = np.full(n, -1, np.int32)
        for b, cnt in enumerate(per_block):
            idx = b * 32 + np.sort(rng.choice(32, cnt, replace=False))
            cand[idx] = (
                rng.integers(0, 3, cnt) if vals is None else vals
            )
        got_v, got_i = lifecycle._top_m_sparse(jnp.asarray(cand), m)
        exp_v, exp_i = jax.lax.top_k(jnp.asarray(cand), m)
        # padding entries (value -1) may legitimately differ in subject:
        # dense uses arbitrary in-range indices, sparse uses n (dropped by
        # every downstream scatter) — compare values always, indices only
        # where a real candidate was selected
        assert np.array_equal(np.asarray(got_v), np.asarray(exp_v)), tag
        real = np.asarray(exp_v) >= 0
        assert np.array_equal(np.asarray(got_i)[real], np.asarray(exp_i)[real]), tag

    check([0] * 16, "empty")  # no candidates at all
    check([2] * 16, "hierarchical")  # every block under cap: local+merge
    check([4] * 16, "boundary")  # == cap in every block: still hierarchical
    check([2] * 15 + [7], "overflow")  # ONE overfull block -> full sort
    # cross-block merge tie-break: more equal-valued candidates than m,
    # spread over many blocks — the winners must be the lowest global
    # indices, which only holds if the merge preserves (block, index) order
    check([1] * 16, "merge-ties", vals=7)
    check([3] * 16, "merge-ties-multi", vals=2)
