"""Golden-trajectory regression: the lifecycle engine must reproduce its
frozen per-tick state evolution BIT-IDENTICALLY.

The trajectories in ``tests/golden/lifecycle_traj.npz`` were captured by
``capture_lifecycle_golden.py`` and span both exchange topologies, packet
loss, partition+heal, the full suspect→faulty→tombstone→evict chain, slot
saturation, K>32/K<32 word tails, heal_prob on/off, and a mid-run admit.
Any representation change inside the engine (layout, fusion structure,
bitpacking) must leave every field of every tick untouched — including
PRNG draw order, tie-breaks, and deadline arithmetic.  A failure here
means protocol semantics moved, not just an optimization.

Reference analog: the tier-3 cross-implementation conformance suite
(``test/run-integration-tests``) pinning protocol behavior; here the other
implementation is the engine's own frozen history.
"""

from __future__ import annotations

import numpy as np
import pytest

from ringpop_tpu.sim import lifecycle

from tests import golden_tools
from tests.capture_lifecycle_golden import CONFIGS, GOLDEN_PATH, run_config

_FIELDS_EXACT = [f for f in lifecycle.LifecycleState._fields]


def _as_bool_plane(arr: np.ndarray, k: int) -> np.ndarray:
    """Unpack a bit-packed [T, N, W] uint32 ``learned`` to [T, N, K] bool;
    pass an already-bool plane through.  The goldens were captured from the
    pre-packing engine, so the comparison is representation-agnostic by
    construction — exactly what lets them certify layout changes."""
    if arr.dtype == np.bool_:
        return arr
    bits = (arr[..., :, None] >> np.arange(32, dtype=np.uint32)) & 1
    return bits.reshape(arr.shape[:-1] + (arr.shape[-1] * 32,))[..., :k].astype(bool)


@pytest.fixture(scope="module")
def golden():
    # dual-toolchain resolution: the npz matching the RUNNING toolchain
    # fingerprint when captured, else the legacy capture (whose mismatch
    # then fails with the drift diagnosis) — tests/golden_tools.py
    return golden_tools.load_golden(GOLDEN_PATH)


@pytest.mark.parametrize(
    "name,pkw,fault_sched,admits,ticks,seed",
    CONFIGS,
    ids=[c[0] for c in CONFIGS],
)
def test_trajectory_bit_identical(golden, name, pkw, fault_sched, admits, ticks, seed):
    traj = run_config(pkw, fault_sched, admits, ticks, seed)
    params = lifecycle.LifecycleParams(**pkw)
    k = params.k
    # fields added to the state after the LEGACY goldens were captured;
    # when the loaded capture predates one, it is pinned by the derived-
    # invariant check below instead — any other missing field is a stale
    # golden and must fail loudly.  Post-r8 (per-fingerprint) captures
    # carry every field and compare exactly.
    post_capture_fields = {"ride_ok"}
    for field in _FIELDS_EXACT:
        if f"{name}/{field}" not in golden.files:
            assert field in post_capture_fields, f"stale golden: missing {field}"
            continue
        want = golden[f"{name}/{field}"]
        got = traj[field]
        if field in ("learned", "ride_ok"):
            want, got = _as_bool_plane(want, k), _as_bool_plane(got, k)
        assert got.shape == want.shape, (field, got.shape, want.shape)
        mism = np.flatnonzero(
            (got != want).reshape(ticks, -1).any(axis=1)
        )
        if mism.size:
            # classify toolchain drift vs real regression instead of a raw
            # array-mismatch assert (ROADMAP: 'Golden trajectories vs
            # toolchain drift')
            golden_tools.fail_golden(golden, name, field, int(mism[0]))
    # the carried ride_ok plane is derived state: its invariant pins it to
    # the golden-checked pcount at every tick
    from ringpop_tpu.sim.delta import clamped_max_p

    max_p = clamped_max_p(params)
    want_ride = traj["pcount"] < max_p
    got_ride = _as_bool_plane(traj["ride_ok"], k)
    assert (got_ride == want_ride).all(), f"{name}: ride_ok invariant broken"
