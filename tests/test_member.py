"""Semantics-core tests: state precedence, override rules, wire shims.
Tables modeled on the reference's swim/member_test.go behavior."""

import numpy as np
import pytest

from ringpop_tpu.swim.member import (
    ALIVE,
    SUSPECT,
    FAULTY,
    LEAVE,
    TOMBSTONE,
    Change,
    Member,
    local_override,
    non_local_override,
    overrides,
    state_id,
    state_name,
    is_reachable,
)

STATES = [ALIVE, SUSPECT, FAULTY, LEAVE, TOMBSTONE]


def test_state_name_roundtrip():
    for s in STATES:
        assert state_id(state_name(s)) == s


def test_precedence_order():
    # member.go:112-128: alive < suspect < faulty < leave < tombstone
    assert ALIVE < SUSPECT < FAULTY < LEAVE < TOMBSTONE


@pytest.mark.parametrize("s_new", STATES)
@pytest.mark.parametrize("s_old", STATES)
def test_override_matrix(s_new, s_old):
    # same incarnation: strictly higher precedence wins (member.go:79-93)
    assert bool(overrides(5, s_new, 5, s_old)) == (s_new > s_old)
    # newer incarnation always wins, older never does
    assert overrides(6, s_new, 5, s_old)
    assert not overrides(4, s_new, 5, s_old)


def test_override_elementwise_on_arrays():
    inc_a = np.array([6, 5, 5, 4])
    st_a = np.array([ALIVE, FAULTY, ALIVE, TOMBSTONE])
    inc_b = np.array([5, 5, 5, 5])
    st_b = np.array([TOMBSTONE, SUSPECT, ALIVE, ALIVE])
    got = overrides(inc_a, st_a, inc_b, st_b)
    assert got.tolist() == [True, True, False, False]


def test_local_override_only_detractions_at_geq_incarnation():
    # member.go:98-110: suspect/faulty/tombstone at inc >= local must refute
    assert local_override(5, SUSPECT, 5)
    assert local_override(6, FAULTY, 5)
    assert local_override(5, TOMBSTONE, 5)
    assert not local_override(4, SUSPECT, 5)
    assert not local_override(9, ALIVE, 5)
    assert not local_override(9, LEAVE, 5)


def test_member_local_override_requires_address_match():
    m = Member("a:1", ALIVE, 5)
    c = Change(address="a:1", incarnation=5, status=SUSPECT)
    assert m.local_override("a:1", c)
    assert not m.local_override("b:2", c)


def test_reachability():
    assert bool(is_reachable(ALIVE)) and bool(is_reachable(SUSPECT))
    for s in (FAULTY, LEAVE, TOMBSTONE):
        assert not bool(is_reachable(s))


def test_wire_roundtrip_plain():
    c = Change(
        address="10.0.0.1:3000",
        incarnation=123456,
        status=SUSPECT,
        source="10.0.0.2:3000",
        source_incarnation=99,
        timestamp=1700000000,
    )
    d = c.to_wire()
    assert d["status"] == "suspect"
    assert d["incarnationNumber"] == 123456
    assert d["sourceIncarnationNumber"] == 99
    assert Change.from_wire(d) == c


def test_wire_tombstone_compat_shim():
    # member.go:150-167: tombstone rides the wire as faulty+flag
    c = Change(address="a:1", incarnation=1, status=TOMBSTONE)
    d = c.to_wire()
    assert d["status"] == "faulty" and d["tombstone"] is True
    back = Change.from_wire(d)
    assert back.status == TOMBSTONE


def test_wire_faulty_without_flag_stays_faulty():
    d = Change(address="a:1", incarnation=1, status=FAULTY).to_wire()
    assert "tombstone" not in d
    assert Change.from_wire(d).status == FAULTY


def test_unknown_wire_status_roundtrips_verbatim():
    # unknown states decode to precedence -1 but must re-serialize unchanged
    # (the reference keeps the string verbatim; member.go:124-127)
    d = {"address": "a:1", "incarnationNumber": 7, "status": "weird-future-state"}
    c = Change.from_wire(d)
    assert c.status == -1
    assert not bool(is_reachable(c.status))
    assert c.to_wire()["status"] == "weird-future-state"
    # and it never overrides anything
    assert not overrides(c.incarnation, c.status, 7, ALIVE)
