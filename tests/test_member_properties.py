"""Randomized property tests for the override lattice.

The whole design rests on one claim (README "two planes, one semantics
core"): the reference's update rules (`member.go:79-128,178-187`,
`memberlist.go:310-390`) form a lattice whose join is ``max`` over
``pack_key(incarnation, state)``, so the host plane's sequential fold and
the sim planes' vectorized maxes compute the same member states.  These
tests pin that claim with seeded random sweeps instead of hand-picked
tables (the tables live in test_member.py).
"""

from __future__ import annotations

import random

import numpy as np

from ringpop_tpu import util
from ringpop_tpu.net.channel import LocalNetwork
from ringpop_tpu.swim.member import (
    ALIVE,
    FAULTY,
    LEAVE,
    SUSPECT,
    TOMBSTONE,
    Change,
    key_incarnation,
    key_state,
    overrides,
    pack_key,
)
from tests.swim_utils import make_node

STATES = [ALIVE, SUSPECT, FAULTY, LEAVE, TOMBSTONE]


def _rand_pairs(rng: random.Random, n: int, max_inc: int = 1 << 27):
    return [(rng.randrange(max_inc), rng.choice(STATES)) for _ in range(n)]


def test_pack_key_is_order_embedding():
    """pack_key(a) > pack_key(b)  <=>  overrides(a, b), over random pairs —
    the property that lets array engines replace the reference's branching
    comparison with one integer max."""
    rng = random.Random(11)
    pairs = _rand_pairs(rng, 400)
    for inc_a, st_a in pairs[:200]:
        for inc_b, st_b in rng.sample(pairs, 20):
            assert (pack_key(inc_a, st_a) > pack_key(inc_b, st_b)) == bool(
                overrides(inc_a, st_a, inc_b, st_b)
            ), (inc_a, st_a, inc_b, st_b)


def test_pack_key_roundtrip_and_array_parity():
    rng = random.Random(12)
    incs = np.array([p[0] for p in _rand_pairs(rng, 1000)], dtype=np.int32)
    sts = np.array([rng.choice(STATES) for _ in range(1000)], dtype=np.int32)
    keys = pack_key(incs, sts)
    np.testing.assert_array_equal(key_incarnation(keys), incs)
    np.testing.assert_array_equal(key_state(keys), sts)
    # scalar and array forms agree elementwise
    for i in range(0, 1000, 97):
        assert int(keys[i]) == pack_key(int(incs[i]), int(sts[i]))


def test_overrides_scalar_vs_array_elementwise():
    rng = random.Random(13)
    a = _rand_pairs(rng, 500)
    b = _rand_pairs(rng, 500)
    inc_a = np.array([x[0] for x in a]); st_a = np.array([x[1] for x in a])
    inc_b = np.array([x[0] for x in b]); st_b = np.array([x[1] for x in b])
    vec = overrides(inc_a, st_a, inc_b, st_b)
    for i in range(500):
        assert bool(vec[i]) == bool(overrides(a[i][0], a[i][1], b[i][0], b[i][1]))


def test_update_fold_equals_lattice_max():
    """Applying a random change sequence about a NON-local member through
    the full memberlist.update pipeline ends at exactly the pack_key max of
    the sequence — order-independence of the consistency core.

    Tombstone-first prefixes are skipped by the pipeline (first-seen
    tombstone refusal, ``memberlist.py:168-170``), so the expected fold
    starts at the first non-tombstone change (exactly the reference's
    re-import guard) and joins everything after it.
    """
    rng = random.Random(14)
    for trial in range(60):
        node = make_node(LocalNetwork(), "10.9.9.9:3000")
        try:
            seq = _rand_pairs(rng, rng.randint(1, 12), max_inc=1000)
            order = list(seq)
            rng.shuffle(order)
            subject = "10.0.0.1:3000"
            for inc, st in order:
                node.memberlist.update(
                    [Change(source="t", source_incarnation=1,
                            address=subject, incarnation=inc, status=st)]
                )
            member = node.memberlist.member(subject)
            # expected: fold with first-seen seeding + override joins,
            # skipping the tombstone-first refusals
            expect = None
            for inc, st in order:
                if expect is None:
                    if st != TOMBSTONE:
                        expect = (inc, st)
                elif pack_key(inc, st) > pack_key(*expect):
                    expect = (inc, st)
            if expect is None:
                assert member is None, "all-tombstone sequence created a member"
            else:
                assert member is not None
                assert (member.incarnation, member.status) == expect, (
                    trial, order, (member.incarnation, member.status), expect
                )
        finally:
            node.destroy()


def test_refutation_wins_once_clock_advances():
    """A detraction echoing any incarnation the local node could have issued
    (i.e. <= its clock, which has since advanced) is refuted by a
    reincarnation that strictly OVERRIDES it (parity:
    ``memberlist.go:337-354``) — the liveness half of the protocol.

    Incarnations are wall-clock ms precisely so this holds without
    coordination: a real detraction carries an incarnation the subject
    issued earlier, so by refutation time now-ms exceeds it."""
    rng = random.Random(15)
    for _ in range(40):
        node = make_node(LocalNetwork(), "10.9.9.9:3000")
        try:
            node.memberlist.reincarnate()
            inc0 = node.memberlist.member(node.address).incarnation
            node.clock.advance(rng.randint(1, 5000) / 1000.0)
            now = util.now_ms(node.clock)
            detraction_inc = rng.randint(inc0, now - 1)
            st = rng.choice([SUSPECT, FAULTY, TOMBSTONE])
            node.memberlist.update(
                [Change(source="t", source_incarnation=1, address=node.address,
                        incarnation=detraction_inc, status=st)]
            )
            me = node.memberlist.member(node.address)
            assert me.status == ALIVE
            assert pack_key(me.incarnation, me.status) > pack_key(detraction_inc, st), (
                "refutation does not override the detraction",
                (me.incarnation, me.status), (detraction_inc, st),
            )
        finally:
            node.destroy()


def test_same_millisecond_detraction_is_reference_faithful():
    """Reference-faithful edge: a detraction at incarnation == now-ms draws
    a refutation at the SAME incarnation, whose Alive does not override the
    detraction (precedence Alive < Suspect at equal incarnation) — exactly
    the reference's behavior (``memberlist.go:337-354`` uses raw
    nowInMillis).  Convergence then relies on the clock advancing before
    the next gossip redelivery, at which point refutation wins (the test
    above).  Pinned so a future 'fix' here knows it would diverge from the
    reference wire behavior."""
    node = make_node(LocalNetwork(), "10.9.9.9:3000")
    try:
        node.memberlist.reincarnate()
        now = util.now_ms(node.clock)
        node.memberlist.update(
            [Change(source="t", source_incarnation=1, address=node.address,
                    incarnation=now, status=SUSPECT)]
        )
        me = node.memberlist.member(node.address)
        # the refutation applied Alive@now locally, which ties (and loses
        # to) Suspect@now under the override order — locally the node still
        # believes itself Alive; remotely the suspect claim survives this ms
        assert me.status == ALIVE
        assert me.incarnation == now
        assert not pack_key(me.incarnation, me.status) > pack_key(now, SUSPECT)
    finally:
        node.destroy()
