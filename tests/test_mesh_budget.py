"""Collective-budget regression guard for the sharded lifecycle engine.

The r6 tentpole cut the sharded 1M-tick's cross-chip traffic ~2.3×
(hierarchical candidate select, blocked row reduces, detect-walk
replication); r8 cut the residue ~2× again by lowering the shift
exchange's roll legs shard-local (``parallel/shift.shard_roll`` — two
crossing blocks per leg as sub-block ppermutes instead of GSPMD's
plane-sized all-gathers) and replacing the replicated threefry
peer-choice draw with the partition-invariant counter RNG
(``sim/prng.py`` — elementwise in the lane, zero collectives, identical
lanes on any mesh).  Nothing in the type system stops a future engine
edit from silently re-globalizing one of those paths — the SPMD
partitioner will happily all-gather an [N]-indexed operand again — so
this test compiles the sharded programs at CI scale (8k × 64 over a 2×2
node × rumor mesh, with the sharded-caller defaults rng="counter" +
exchange_mesh) and asserts the collective census stays at or under the
post-r8 budget.

Counting convention (r8): budgets are over the worst-case EXECUTED
collective set (``profile_mesh.executed_rows``) — sibling branches of a
``conditional`` (the exchange's shift switch, the sparse-select
fallback) are mutually exclusive per tick, so each conditional charges
only its most expensive branch.

Budgets are the measured values plus slack for partitioner noise
(measured at this config: step 130 executed collectives / 0.39 MB; walk
body 1 collective): blowing one is not flaky infrastructure, it is an
ICI-traffic regression — run scripts/profile_mesh.py to attribute the
new collective before raising any number here.
"""

from __future__ import annotations

import dataclasses
import functools
import importlib.util
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ringpop_tpu.sim import lifecycle
from ringpop_tpu.sim.delta import DeltaFaults

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# measured 130 / 0.386 MB at this config (see module docstring)
STEP_MAX_COLLECTIVES = 145
STEP_MAX_MB = 0.50
# the detection walk's fori body must stay at <= 1 collective per
# iteration — the acceptance bar of the r6 detect-walk replication
# (down from ~6/iteration when the packed plane was gathered per slot)
WALK_MAX_COLLECTIVES_PER_ITER = 1
# the shift exchange: each roll leg's crossing window spans H+1 sub-blocks
# on two source shards, so H+1 ppermutes per rolled leaf per leg is the
# floor of the decomposition (ONE collective per crossing sub-block; a
# single collective per leg is unattainable for a traced shift, which is
# exactly why GSPMD all-gathers it).  Three rolled leaves per tick (sent
# plane + delivered vector on the request leg, answerable plane on the
# response leg), H = 2, self-sends skipped => <= 9 executed ppermutes,
# and NO gather-class collectives bigger than a scalar broadcast.
EXCHANGE_MAX_PPERMUTES = 9
EXCHANGE_MAX_OTHER_BYTES = 16 * 1024


def _profile_mesh_module():
    spec = importlib.util.spec_from_file_location(
        "profile_mesh", os.path.join(_REPO, "scripts", "profile_mesh.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _census_of(compiled_text: str, tmp_path):
    pm = _profile_mesh_module()
    p = tmp_path / "budget_hlo.txt"
    p.write_text(compiled_text)
    return pm.parse_collectives(str(p))


def _executed(census):
    """(count, bytes) over the worst-case executed collective set."""
    pm = _profile_mesh_module()
    rows = [r for _, r in pm.executed_rows(census)]
    return len(rows), sum(r["bytes"] for r in rows)


@pytest.fixture(scope="module")
def sharded_setup():
    devs = np.asarray(jax.devices("cpu")[:4]).reshape(2, 2)
    mesh = Mesh(devs, ("node", "rumor"))
    n, k = 8192, 64
    # the sharded-caller defaults this suite budgets: counter RNG +
    # shard-local exchange legs.  plain_params is the same protocol run
    # unsharded (no mesh hint) — the bit-equality reference.
    plain_params = lifecycle.LifecycleParams(n=n, k=k, suspect_ticks=10, rng="counter")
    params = dataclasses.replace(plain_params, exchange_mesh=mesh)
    up = np.ones(n, bool)
    up[::64] = False
    faults = DeltaFaults(up=jnp.asarray(up))
    state = jax.tree.map(
        jax.device_put,
        lifecycle.init_state(params, seed=0),
        lifecycle.state_shardings(mesh, k=k),
    )
    return mesh, params, plain_params, state, faults, up


def test_step_collective_budget(sharded_setup, tmp_path, monkeypatch):
    """The sharded one-tick program's executed collective count/bytes stay
    at or under the post-r8 budget (hierarchical select engaged via the
    MIN_N monkeypatch, exactly as the 1M program runs it)."""
    mesh, params, _, state, faults, _ = sharded_setup
    monkeypatch.setattr(lifecycle, "_SPARSE_TOPK_MIN_N", 0)
    blk = jax.jit(
        functools.partial(lifecycle._run_block, params), static_argnames="ticks"
    )
    census = _census_of(
        blk.lower(state, faults, ticks=1).compile().as_text(), tmp_path
    )
    count, nbytes = _executed(census)
    mb = nbytes / 1e6
    assert count > 0, "census parsed no collectives — parser/format drift?"
    assert count <= STEP_MAX_COLLECTIVES, (
        f"sharded step now issues {count} collectives "
        f"(budget {STEP_MAX_COLLECTIVES}) — an engine edit re-globalized "
        "a hot path; run scripts/profile_mesh.py to attribute it"
    )
    assert mb <= STEP_MAX_MB, (
        f"sharded step now moves {mb:.3f} MB/chip/tick (budget "
        f"{STEP_MAX_MB}) — run scripts/profile_mesh.py to attribute it"
    )


def test_exchange_legs_shard_local(sharded_setup, tmp_path, monkeypatch):
    """The r8 exchange acceptance bar: the rumor-exchange phase lowers to
    crossing-block ppermutes ONLY — bounded by H+1 sends per rolled leaf
    per leg (one collective per crossing sub-block; see
    EXCHANGE_MAX_PPERMUTES) — with no plane-sized gather-class collective
    left.  A traced-shift roll that re-globalizes shows up here as the
    all-gather coming back."""
    mesh, params, _, state, faults, _ = sharded_setup
    monkeypatch.setattr(lifecycle, "_SPARSE_TOPK_MIN_N", 0)
    pm = _profile_mesh_module()
    blk = jax.jit(
        functools.partial(lifecycle._run_block, params), static_argnames="ticks"
    )
    census = _census_of(
        blk.lower(state, faults, ticks=1).compile().as_text(), tmp_path
    )
    exch = {}
    for _, r in pm.executed_rows(census):
        if r.get("phase") in ("rumor-exchange", "shard-roll"):
            e = exch.setdefault(r["kind"], {"count": 0, "bytes": 0})
            e["count"] += 1
            e["bytes"] += r["bytes"]
    pp = exch.pop("collective-permute", {"count": 0, "bytes": 0})
    assert pp["count"] > 0, "exchange phase shows no ppermutes — census drift?"
    assert pp["count"] <= EXCHANGE_MAX_PPERMUTES, (
        f"exchange legs now execute {pp['count']} ppermutes "
        f"(budget {EXCHANGE_MAX_PPERMUTES} = (H+1) per rolled leaf per leg)"
    )
    other = sum(e["bytes"] for e in exch.values())
    assert other <= EXCHANGE_MAX_OTHER_BYTES, (
        f"exchange phase moves {other} bytes of non-ppermute collectives "
        f"({exch}) — the traced-shift roll re-globalized"
    )


def test_pipelined_exchange_census_identical_to_sequential(
    sharded_setup, tmp_path, monkeypatch
):
    """The r11 acceptance bar: the fused pipelined leg loop compiles to
    EXACTLY the sequential legs' executed collective set — same count,
    same bytes (the pipeline reorders the dependency graph, it moves no
    extra data).  The r8/r10 budgets therefore hold unchanged under the
    new default."""
    mesh, params, _, state, faults, _ = sharded_setup
    monkeypatch.setattr(lifecycle, "_SPARSE_TOPK_MIN_N", 0)
    seq_params = dataclasses.replace(params, exchange_pipelined=False)
    blk_p = jax.jit(
        functools.partial(lifecycle._run_block, params), static_argnames="ticks"
    )
    blk_s = jax.jit(
        functools.partial(lifecycle._run_block, seq_params), static_argnames="ticks"
    )
    pipe = _census_of(blk_p.lower(state, faults, ticks=1).compile().as_text(), tmp_path)
    seq = _census_of(blk_s.lower(state, faults, ticks=1).compile().as_text(), tmp_path)
    n_p, b_p = _executed(pipe)
    n_s, b_s = _executed(seq)
    assert n_p > 0, "census parsed no collectives — parser/format drift?"
    assert (n_p, b_p) == (n_s, b_s), (
        f"pipelined exchange compiles to {n_p} collectives / {b_p} B vs "
        f"{n_s} / {b_s} sequential — the fused leg loop moved extra data "
        "(run scripts/profile_mesh.py --exchange shardmap-seq to attribute)"
    )


def test_pipelined_exchange_overlap_in_compiled_schedule(sharded_setup, tmp_path, monkeypatch):
    """The overlap claim itself, statically: in the compiled pipelined
    step at least one exchange region issues a crossing send that
    depends on another permute THROUGH merge compute (analysis/overlap) —
    and the sequential program shows none (the analyzer is not vacuous)."""
    from ringpop_tpu.analysis import overlap as _overlap

    mesh, params, _, state, faults, _ = sharded_setup
    monkeypatch.setattr(lifecycle, "_SPARSE_TOPK_MIN_N", 0)
    seq_params = dataclasses.replace(params, exchange_pipelined=False)
    for p, expect in ((params, True), (seq_params, False)):
        blk = jax.jit(
            functools.partial(lifecycle._run_block, p), static_argnames="ticks"
        )
        path = tmp_path / f"overlap_{expect}.txt"
        path.write_text(blk.lower(state, faults, ticks=1).compile().as_text())
        rep = _overlap.analyze(str(path))
        assert rep["overlap"] is expect, (
            f"exchange_pipelined={p.exchange_pipelined}: overlap analyzer "
            f"reported {rep['overlap']} (regions: "
            f"{[(r['computation'], len(r['dependent_sends'])) for r in rep['regions']]})"
        )


def test_peer_choice_phase_zero_collectives(sharded_setup, tmp_path, monkeypatch):
    """The r8 RNG acceptance bar: under rng="counter" the peer-choice
    phase carries ZERO cross-chip collectives — the [N, P] draw is
    elementwise in (node, column), so the partitioner keeps every lane on
    the shard that owns it (threefry materialized it replicated:
    ~12 MB/chip/tick all-reduce at 1M, and divergent lanes)."""
    mesh, params, _, state, faults, _ = sharded_setup
    monkeypatch.setattr(lifecycle, "_SPARSE_TOPK_MIN_N", 0)
    pm = _profile_mesh_module()
    blk = jax.jit(
        functools.partial(lifecycle._run_block, params), static_argnames="ticks"
    )
    census = _census_of(
        blk.lower(state, faults, ticks=1).compile().as_text(), tmp_path
    )
    peer = [r for _, r in pm.executed_rows(census) if r.get("phase") == "peer-choice"]
    assert not peer, (
        f"peer-choice phase now carries collectives {peer} — the counter "
        "draw stopped being shard-local"
    )


def test_shard_roll_matches_gather_path(sharded_setup):
    """Value-identity of the shard-local exchange: one sharded tick with
    exchange_mesh set is bit-equal to the same tick through the
    materialized-index gather path, across shifts in every (q, r) class
    of the sub-block decomposition — exercised by stepping from distinct
    seeds (each tick draws a fresh shift).  This is the paired
    old-vs-new certificate at engine level; parallel/shift.py's sweep
    lives in the docstringed derivation."""
    mesh, params, plain_params, state, faults, _ = sharded_setup
    sm = jax.jit(functools.partial(lifecycle.step, params))
    gather = jax.jit(
        functools.partial(lifecycle.step, dataclasses.replace(params, exchange_mesh=None))
    )
    st = state
    for _ in range(6):
        a = sm(st, faults)
        b = gather(st, faults)
        for la, lb in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
            assert bool((np.asarray(la) == np.asarray(lb)).all())
        st = a


def test_detect_walk_body_collective_budget(sharded_setup, tmp_path):
    """With the rumor-axis replication hint, the detection walk's
    while-body carries <= 1 collective per iteration (the finalize
    scalar reduce) — the K-sequential-collectives pathology stays dead.
    ``detection_complete`` holds exactly one loop (the K-slot walk), so
    every loop-depth >= 1 computation in its HLO is walk body."""
    mesh, params, _, state, faults, up = sharded_setup
    subjects = jnp.asarray(np.flatnonzero(~up)[:32], jnp.int32)
    jdc = jax.jit(
        lifecycle.detection_complete,
        static_argnames=("min_status", "learned_sharding"),
    )
    census = _census_of(
        jdc.lower(
            state,
            subjects,
            faults,
            min_status=lifecycle.FAULTY,
            learned_sharding=NamedSharding(mesh, P("node", None)),
        )
        .compile()
        .as_text(),
        tmp_path,
    )
    body_comps = {
        c: rows
        for c, rows in census["computations"].items()
        if census["loop_depth"].get(c, 0) >= 1
    }
    total_entry = sum(len(v) for v in census["computations"].values())
    assert total_entry > 0, "census parsed no collectives — parser/format drift?"
    for comp, rows in body_comps.items():
        assert len(rows) <= WALK_MAX_COLLECTIVES_PER_ITER, (
            f"walk-body computation {comp} carries {len(rows)} collectives "
            f"per iteration ({[r['kind'] for r in rows]}) — the detect walk "
            "is paying cross-shard traffic inside the K-slot loop again"
        )


def test_telemetry_adds_zero_per_tick_collectives(sharded_setup, tmp_path, monkeypatch):
    """The telemetry-plane acceptance bar (ISSUE 2): carrying the counter
    accumulators through the sharded step adds ZERO collectives per tick —
    every accumulator update is elementwise, so the partitioner keeps it
    shard-local.  Asserted as census equality between the telemetry-off
    and telemetry-on compilations of the same one-tick block."""
    from ringpop_tpu.sim import telemetry

    mesh, params, _, state, faults, _ = sharded_setup
    monkeypatch.setattr(lifecycle, "_SPARSE_TOPK_MIN_N", 0)
    blk = jax.jit(
        functools.partial(lifecycle._run_block, params), static_argnames="ticks"
    )
    off = _census_of(blk.lower(state, faults, ticks=1).compile().as_text(), tmp_path)
    tel = telemetry.zeros(params)
    on = _census_of(
        blk.lower(state, faults, ticks=1, telemetry=tel).compile().as_text(),
        tmp_path,
    )
    n_off, b_off = _executed(off)
    n_on, b_on = _executed(on)
    assert n_off > 0, "census parsed no collectives — parser/format drift?"
    assert n_on == n_off, (
        f"telemetry-on step compiles to {n_on} collectives vs {n_off} "
        "telemetry-off — an accumulator update stopped being elementwise"
    )
    assert b_on == b_off, (n_on, b_on, b_off)


def test_telemetry_fetch_is_psum_only_per_block(sharded_setup, tmp_path):
    """The once-per-block fetch reduction compiles to psum-class
    collectives only (all-reduce / reduce-scatter) — no gathers or
    permutes: the counters leave the mesh as scalars, one reduction per
    counter per fetched block."""
    from ringpop_tpu.sim import telemetry

    mesh, params, _, state, faults, _ = sharded_setup
    tel = telemetry.zeros(params)
    jfetch = jax.jit(telemetry.fetch)
    census = _census_of(
        jfetch.lower(tel, state, faults).compile().as_text(), tmp_path
    )
    kinds = {r["kind"] for v in census["computations"].values() for r in v}
    assert kinds <= {"all-reduce", "reduce-scatter"}, (
        f"telemetry fetch moved non-psum collectives across the mesh: {kinds}"
    )


def test_sharded_telemetry_run_matches_unsharded(sharded_setup):
    """Execute (not just compile) the telemetry-carrying block over the
    mesh: state AND fetched counters must be bit-equal to the unsharded
    run — INCLUDING ``ping_req_send``.

    History: under rng="threefry" this equality held only loosely —
    threefry is non-partitionable, so the sharded [N, P] peer draw
    generated different lanes than the unsharded program (~100% of
    lanes; r7 finding, state-invisible at the committed configs only
    because ``up[targets]`` masked every lane that could matter).  The
    counter RNG closes that hole: every lane is a pure function of
    (seed, tick, lane, draw site), so the sharded and unsharded programs
    sample identically and the exact assertion below is the r8
    acceptance bar."""
    from ringpop_tpu.sim import telemetry

    mesh, params, plain_params, sstate, faults, up = sharded_setup
    sm_blk = jax.jit(
        functools.partial(lifecycle._run_block, params), static_argnames="ticks"
    )
    ref_blk = jax.jit(
        functools.partial(lifecycle._run_block, plain_params), static_argnames="ticks"
    )
    ref_s, ref_t = ref_blk(
        lifecycle.init_state(plain_params, seed=0), faults, ticks=4,
        telemetry=telemetry.zeros(plain_params),
    )
    sh_s, sh_t = sm_blk(sstate, faults, ticks=4, telemetry=telemetry.zeros(params))
    for a, b in zip(jax.tree.leaves(ref_s), jax.tree.leaves(sh_s)):
        assert bool((np.asarray(a) == np.asarray(b)).all())
    ref_rec, _ = telemetry.fetch(ref_t, ref_s, faults)
    sh_rec, _ = telemetry.fetch(sh_t, sh_s, faults)
    ref_rec, sh_rec = jax.device_get((ref_rec, sh_rec))
    for key in ref_rec:
        assert np.asarray(ref_rec[key]) == np.asarray(sh_rec[key]), key


def test_chaos_plan_adds_zero_per_tick_collectives(sharded_setup, tmp_path, monkeypatch):
    """The chaos-plane acceptance bar (ISSUE 5): driving the sharded step
    with a time-varying churn+flap+loss FaultPlan (the same liveness
    overlay as the static model) compiles to EXACTLY the static
    program's executed collective set — fault-timeline evaluation is
    elementwise in the node lane, so the partitioner keeps it
    shard-local.  Census equality, like the telemetry bar above."""
    from ringpop_tpu.sim import chaos

    mesh, params, _, state, faults, up = sharded_setup
    monkeypatch.setattr(lifecycle, "_SPARSE_TOPK_MIN_N", 0)
    blk = jax.jit(
        functools.partial(lifecycle._run_block, params), static_argnames="ticks"
    )
    plain = _census_of(blk.lower(state, faults, ticks=1).compile().as_text(), tmp_path)
    plan = chaos._merge_plans(
        chaos.scenario_plan("smoke", params.n, seed=0, horizon=64),
        chaos.FaultPlan(base_up=jnp.asarray(up)),
    )
    with_plan = _census_of(
        blk.lower(state, plan, ticks=1).compile().as_text(), tmp_path
    )
    n_plain, b_plain = _executed(plain)
    n_chaos, b_chaos = _executed(with_plan)
    assert n_plain > 0, "census parsed no collectives — parser/format drift?"
    assert (n_chaos, b_chaos) == (n_plain, b_plain), (
        f"chaos-enabled step compiles to {n_chaos} collectives / {b_chaos} B "
        f"vs {n_plain} / {b_plain} static — fault evaluation stopped being "
        "shard-local (run scripts/profile_mesh.py --chaos to attribute it)"
    )


def test_full_chaos_plan_forbidden_phases_stay_empty(sharded_setup, tmp_path, monkeypatch):
    """With EVERY chaos leg active — churn, flap, a directed partition
    window (reach) and per-node drop — the compiled sharded step keeps
    the forbidden phases empty: no collective in fault-plan (timeline
    evaluation) or peer-choice (the counter draws).  The reach/drop_node
    gathers themselves land in their consuming phases and are budgeted
    there; this test pins the phases that must stay at ZERO."""
    from ringpop_tpu.sim import chaos

    mesh, params, _, state, faults, up = sharded_setup
    monkeypatch.setattr(lifecycle, "_SPARSE_TOPK_MIN_N", 0)
    pm = _profile_mesh_module()
    n = params.n
    group = np.zeros(n, np.int32)
    group[: n // 3] = 1
    dn = np.zeros(n, np.float32)
    dn[::64] = 0.2
    plan = chaos._merge_plans(
        chaos.scenario_plan("smoke", n, seed=0, horizon=64),
        chaos.FaultPlan(
            base_up=jnp.asarray(up),
            group=jnp.asarray(group),
            part_from=jnp.asarray(np.int32(0)),
            part_until=jnp.asarray(np.int32(48)),
            reach=jnp.asarray(np.asarray([[True, False], [True, True]])),
            drop_node=jnp.asarray(dn),
        ),
    )
    blk = jax.jit(
        functools.partial(lifecycle._run_block, params), static_argnames="ticks"
    )
    census = _census_of(blk.lower(state, plan, ticks=1).compile().as_text(), tmp_path)
    rows = [r for _, r in pm.executed_rows(census)]
    assert rows, "census parsed no collectives — parser/format drift?"
    bad = [r for r in rows if r.get("phase") in ("fault-plan", "peer-choice")]
    assert not bad, (
        f"forbidden phases carry collectives under the full chaos plan: {bad}"
    )


def test_sharded_chaos_run_matches_unsharded(sharded_setup):
    """Execute (not just compile) the chaos-enabled block over the mesh:
    a time-varying churn+flap+loss plan must land bit-equal to the
    unsharded run — the r8 partition-invariance bar extended to the
    chaos plane (the simbench chaos scenarios certify the same property
    per scenario via their sharded-twin subprocess)."""
    from ringpop_tpu.sim import chaos

    mesh, params, plain_params, sstate, faults, up = sharded_setup
    plan = chaos._merge_plans(
        chaos.scenario_plan("smoke", params.n, seed=0, horizon=64),
        chaos.FaultPlan(base_up=jnp.asarray(up)),
    )
    sm_blk = jax.jit(
        functools.partial(lifecycle._run_block, params), static_argnames="ticks"
    )
    ref_blk = jax.jit(
        functools.partial(lifecycle._run_block, plain_params), static_argnames="ticks"
    )
    ref = ref_blk(lifecycle.init_state(plain_params, seed=0), plan, ticks=6)
    sh = sm_blk(sstate, plan, ticks=6)
    for a, b in zip(jax.tree.leaves(ref), jax.tree.leaves(sh)):
        assert bool((np.asarray(a) == np.asarray(b)).all())


def test_detect_census_sees_unhinted_walk_collectives(sharded_setup, tmp_path):
    """Self-check that the budget numbers are not vacuous: the UNhinted
    detect program (no learned_sharding) must show MORE walk-body
    collectives than the hinted one — proving the parser can see
    in-body collectives at all, and that the hint is what removes them."""
    mesh, params, _, state, faults, up = sharded_setup
    subjects = jnp.asarray(np.flatnonzero(~up)[:32], jnp.int32)
    jdc = jax.jit(
        lifecycle.detection_complete,
        static_argnames=("min_status", "learned_sharding"),
    )
    census = _census_of(
        jdc.lower(state, subjects, faults, min_status=lifecycle.FAULTY)
        .compile()
        .as_text(),
        tmp_path,
    )
    body = sum(
        len(rows)
        for c, rows in census["computations"].items()
        if census["loop_depth"].get(c, 0) >= 1
    )
    assert body > WALK_MAX_COLLECTIVES_PER_ITER, (
        "unhinted walk shows no extra in-body collectives — either the "
        "partitioner learned to hoist the gather itself (budget test can "
        "be tightened) or the census stopped seeing loop bodies"
    )
