"""Collective-budget regression guard for the sharded lifecycle engine.

The r6 tentpole cut the sharded 1M-tick's cross-chip traffic ~2.3×
(PERF.md "Multi-chip collective cost model", captures/mesh_profile_r6_*)
by making candidate selection hierarchical, blocking the packed row
reduces, and replicating the detection walk's learned plane once per
check.  Nothing in the type system stops a future engine edit from
silently re-globalizing one of those paths — the SPMD partitioner will
happily all-gather an [N]-indexed operand again — so this test compiles
the sharded programs at CI scale (8k × 64 over a 2×2 node × rumor mesh;
--force-sparse-equivalent monkeypatch so the hierarchical select engages
exactly as it does at 1M) and asserts the collective census stays at or
under the post-tentpole budget.

Budgets are the r6 measured values plus slack for partitioner noise
(measured: step 134 collectives / 0.60 MB; walk body 1 collective):
blowing one is not flaky infrastructure, it is an ICI-traffic
regression — profile scripts/profile_mesh.py to find the new collective
before raising any number here.
"""

from __future__ import annotations

import functools
import importlib.util
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ringpop_tpu.sim import lifecycle
from ringpop_tpu.sim.delta import DeltaFaults

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# measured 134 / 0.603 MB at this config (see module docstring)
STEP_MAX_COLLECTIVES = 150
STEP_MAX_MB = 0.80
# the detection walk's fori body must stay at <= 1 collective per
# iteration — the acceptance bar of the r6 detect-walk replication
# (down from ~6/iteration when the packed plane was gathered per slot)
WALK_MAX_COLLECTIVES_PER_ITER = 1


def _profile_mesh_module():
    spec = importlib.util.spec_from_file_location(
        "profile_mesh", os.path.join(_REPO, "scripts", "profile_mesh.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _census_of(compiled_text: str, tmp_path):
    pm = _profile_mesh_module()
    p = tmp_path / "budget_hlo.txt"
    p.write_text(compiled_text)
    return pm.parse_collectives(str(p))


@pytest.fixture(scope="module")
def sharded_setup():
    devs = np.asarray(jax.devices("cpu")[:4]).reshape(2, 2)
    mesh = Mesh(devs, ("node", "rumor"))
    n, k = 8192, 64
    params = lifecycle.LifecycleParams(n=n, k=k, suspect_ticks=10)
    up = np.ones(n, bool)
    up[::64] = False
    faults = DeltaFaults(up=jnp.asarray(up))
    state = jax.tree.map(
        jax.device_put,
        lifecycle.init_state(params, seed=0),
        lifecycle.state_shardings(mesh, k=k),
    )
    return mesh, params, state, faults, up


def test_step_collective_budget(sharded_setup, tmp_path, monkeypatch):
    """The sharded one-tick program's collective count/bytes stay at or
    under the post-r6 budget (hierarchical select engaged via the MIN_N
    monkeypatch, exactly as the 1M program runs it)."""
    mesh, params, state, faults, _ = sharded_setup
    monkeypatch.setattr(lifecycle, "_SPARSE_TOPK_MIN_N", 0)
    blk = jax.jit(
        functools.partial(lifecycle._run_block, params), static_argnames="ticks"
    )
    census = _census_of(
        blk.lower(state, faults, ticks=1).compile().as_text(), tmp_path
    )
    count = sum(len(v) for v in census["computations"].values())
    mb = sum(
        r["bytes"] for v in census["computations"].values() for r in v
    ) / 1e6
    assert count > 0, "census parsed no collectives — parser/format drift?"
    assert count <= STEP_MAX_COLLECTIVES, (
        f"sharded step now issues {count} collectives "
        f"(budget {STEP_MAX_COLLECTIVES}) — an engine edit re-globalized "
        "a hot path; run scripts/profile_mesh.py to attribute it"
    )
    assert mb <= STEP_MAX_MB, (
        f"sharded step now moves {mb:.3f} MB/chip/tick (budget "
        f"{STEP_MAX_MB}) — run scripts/profile_mesh.py to attribute it"
    )


def test_detect_walk_body_collective_budget(sharded_setup, tmp_path):
    """With the rumor-axis replication hint, the detection walk's
    while-body carries <= 1 collective per iteration (the finalize
    scalar reduce) — the K-sequential-collectives pathology stays dead.
    ``detection_complete`` holds exactly one loop (the K-slot walk), so
    every loop-depth >= 1 computation in its HLO is walk body."""
    mesh, params, state, faults, up = sharded_setup
    subjects = jnp.asarray(np.flatnonzero(~up)[:32], jnp.int32)
    jdc = jax.jit(
        lifecycle.detection_complete,
        static_argnames=("min_status", "learned_sharding"),
    )
    census = _census_of(
        jdc.lower(
            state,
            subjects,
            faults,
            min_status=lifecycle.FAULTY,
            learned_sharding=NamedSharding(mesh, P("node", None)),
        )
        .compile()
        .as_text(),
        tmp_path,
    )
    body_comps = {
        c: rows
        for c, rows in census["computations"].items()
        if census["loop_depth"].get(c, 0) >= 1
    }
    total_entry = sum(len(v) for v in census["computations"].values())
    assert total_entry > 0, "census parsed no collectives — parser/format drift?"
    for comp, rows in body_comps.items():
        assert len(rows) <= WALK_MAX_COLLECTIVES_PER_ITER, (
            f"walk-body computation {comp} carries {len(rows)} collectives "
            f"per iteration ({[r['kind'] for r in rows]}) — the detect walk "
            "is paying cross-shard traffic inside the K-slot loop again"
        )


def test_telemetry_adds_zero_per_tick_collectives(sharded_setup, tmp_path, monkeypatch):
    """The telemetry-plane acceptance bar (ISSUE 2): carrying the counter
    accumulators through the sharded step adds ZERO collectives per tick —
    every accumulator update is elementwise, so the partitioner keeps it
    shard-local.  Asserted as census equality between the telemetry-off
    and telemetry-on compilations of the same one-tick block."""
    from ringpop_tpu.sim import telemetry

    mesh, params, state, faults, _ = sharded_setup
    monkeypatch.setattr(lifecycle, "_SPARSE_TOPK_MIN_N", 0)
    blk = jax.jit(
        functools.partial(lifecycle._run_block, params), static_argnames="ticks"
    )
    off = _census_of(blk.lower(state, faults, ticks=1).compile().as_text(), tmp_path)
    tel = telemetry.zeros(params)
    on = _census_of(
        blk.lower(state, faults, ticks=1, telemetry=tel).compile().as_text(),
        tmp_path,
    )
    n_off = sum(len(v) for v in off["computations"].values())
    n_on = sum(len(v) for v in on["computations"].values())
    assert n_off > 0, "census parsed no collectives — parser/format drift?"
    assert n_on == n_off, (
        f"telemetry-on step compiles to {n_on} collectives vs {n_off} "
        "telemetry-off — an accumulator update stopped being elementwise"
    )
    b_off = sum(r["bytes"] for v in off["computations"].values() for r in v)
    b_on = sum(r["bytes"] for v in on["computations"].values() for r in v)
    assert b_on == b_off, (n_on, b_on, b_off)


def test_telemetry_fetch_is_psum_only_per_block(sharded_setup, tmp_path):
    """The once-per-block fetch reduction compiles to psum-class
    collectives only (all-reduce / reduce-scatter) — no gathers or
    permutes: the counters leave the mesh as scalars, one reduction per
    counter per fetched block."""
    from ringpop_tpu.sim import telemetry

    mesh, params, state, faults, _ = sharded_setup
    tel = telemetry.zeros(params)
    jfetch = jax.jit(telemetry.fetch)
    census = _census_of(
        jfetch.lower(tel, state, faults).compile().as_text(), tmp_path
    )
    kinds = {r["kind"] for v in census["computations"].values() for r in v}
    assert kinds <= {"all-reduce", "reduce-scatter"}, (
        f"telemetry fetch moved non-psum collectives across the mesh: {kinds}"
    )


def test_sharded_telemetry_run_matches_unsharded(sharded_setup):
    """Execute (not just compile) the telemetry-carrying block over the
    mesh: state AND fetched counters must be bit-equal to the unsharded
    run — the counters are reductions of deterministic integer masks.

    Exception, asserted loosely: ``ping_req_send`` counts peer_ok lanes of
    the [N, P] peer-sampling draw, and with ``jax_threefry_partitionable``
    off the SPMD partitioner generates DIFFERENT lanes for a sharded
    output than the unsharded program does (verified directly: ~100% of
    lanes differ).  The protocol state is immune — ``peer_reaches`` is
    masked by ``up[targets]`` for every probing node whose target is
    actually down, and all-peers-invalid is ~1e-6 per probe — which is
    why the r6 sharded bit-equality certifications hold; the counter
    faithfully reports what the sharded program actually sampled.  The
    ROADMAP's "replicated peer-choice PRNG" item is the real fix."""
    from ringpop_tpu.sim import telemetry

    mesh, params, sstate, faults, up = sharded_setup
    blk = jax.jit(
        functools.partial(lifecycle._run_block, params), static_argnames="ticks"
    )
    ref_s, ref_t = blk(
        lifecycle.init_state(params, seed=0), faults, ticks=4,
        telemetry=telemetry.zeros(params),
    )
    sh_s, sh_t = blk(sstate, faults, ticks=4, telemetry=telemetry.zeros(params))
    for a, b in zip(jax.tree.leaves(ref_s), jax.tree.leaves(sh_s)):
        assert bool((np.asarray(a) == np.asarray(b)).all())
    ref_rec, _ = telemetry.fetch(ref_t, ref_s, faults)
    sh_rec, _ = telemetry.fetch(sh_t, sh_s, faults)
    ref_rec, sh_rec = jax.device_get((ref_rec, sh_rec))
    for key in ref_rec:
        if key == "ping_req_send":  # sharded peer-draw lanes (docstring)
            assert abs(int(ref_rec[key]) - int(sh_rec[key])) <= int(
                0.1 * max(int(ref_rec[key]), 1)
            )
            continue
        assert np.asarray(ref_rec[key]) == np.asarray(sh_rec[key]), key


def test_detect_census_sees_unhinted_walk_collectives(sharded_setup, tmp_path):
    """Self-check that the budget numbers are not vacuous: the UNhinted
    detect program (no learned_sharding) must show MORE walk-body
    collectives than the hinted one — proving the parser can see
    in-body collectives at all, and that the hint is what removes them."""
    mesh, params, state, faults, up = sharded_setup
    subjects = jnp.asarray(np.flatnonzero(~up)[:32], jnp.int32)
    jdc = jax.jit(
        lifecycle.detection_complete,
        static_argnames=("min_status", "learned_sharding"),
    )
    census = _census_of(
        jdc.lower(state, subjects, faults, min_status=lifecycle.FAULTY)
        .compile()
        .as_text(),
        tmp_path,
    )
    body = sum(
        len(rows)
        for c, rows in census["computations"].items()
        if census["loop_depth"].get(c, 0) >= 1
    )
    assert body > WALK_MAX_COLLECTIVES_PER_ITER, (
        "unhinted walk shows no extra in-body collectives — either the "
        "partitioner learned to hoist the gather itself (budget test can "
        "be tightened) or the census stopped seeing loop bodies"
    )
