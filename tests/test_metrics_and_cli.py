"""Direct coverage for the metrics primitives, the accelerator probe, and a
simbench CLI smoke — the pieces everything else uses indirectly (gossip
rate tuning, bench orchestration, the committed SIMBENCH artifacts) but no
test exercised by name."""

import json
import os
import subprocess
import sys

import pytest

from ringpop_tpu.util.clock import MockClock
from ringpop_tpu.util.metrics import Histogram, Meter


def test_histogram_reservoir_and_percentiles():
    h = Histogram(sample_size=100, seed=1)
    for v in range(1, 101):
        h.update(float(v))
    assert h.count == 100
    assert h.min() == 1.0 and h.max() == 100.0
    assert abs(h.mean() - 50.5) < 1e-9
    # exact sample → interpolated percentiles land inside the data range
    p50, p99 = h.percentiles([0.5, 0.99])
    assert 49.0 <= p50 <= 52.0
    assert p99 >= 99.0
    # past sample_size the reservoir keeps a bounded uniform sample
    for v in range(101, 1001):
        h.update(float(v))
    assert h.count == 1000
    assert len(h._sample) == 100


def test_histogram_empty_is_zero():
    h = Histogram()
    assert h.percentile(0.5) == 0.0
    assert h.mean() == 0.0 and h.min() == 0.0 and h.max() == 0.0


def test_meter_ewma_rate_with_mock_clock():
    clock = MockClock()
    m = Meter(clock=clock)
    assert m.rate1() == 0.0
    # 10 events/s sustained for a minute converges toward 10/s
    for _ in range(12):
        for _ in range(50):
            m.mark()
        clock.advance(5.0)
    assert m.count == 600
    assert 5.0 < m.rate1() <= 10.5


def test_accel_probe_contract():
    """The probe must always return the diagnostic dict the bench artifacts
    embed, within its timeout, whatever the tunnel is doing.  (It cannot
    assert alive=True even pinned to CPU: this environment's accelerator
    site hook can initialize during jax import regardless of JAX_PLATFORMS
    and hang when the tunnel is wedged — the exact failure mode the
    subprocess probe exists to contain.)"""
    from ringpop_tpu.util.accel import probe_accelerator

    env_backup = os.environ.get("JAX_PLATFORMS")
    os.environ["JAX_PLATFORMS"] = "cpu"
    try:
        probe = probe_accelerator(timeouts_s=(45.0,))
    finally:
        if env_backup is None:
            os.environ.pop("JAX_PLATFORMS", None)
        else:
            os.environ["JAX_PLATFORMS"] = env_backup
    assert set(probe) == {"alive", "platform", "probe_s", "reason"}
    assert isinstance(probe["alive"], bool)
    assert probe["probe_s"] > 0
    if probe["alive"]:
        assert isinstance(probe["platform"], str) and probe["reason"] == "ok"
    else:
        assert probe["reason"] != "ok"


@pytest.mark.slow
def test_simbench_cli_smoke():
    """One scenario end-to-end through the CLI entry point (the artifact
    generator for SIMBENCH_r{N}.json): emits a JSON line with the
    platform/scale fields the committed artifacts carry."""
    r = subprocess.run(
        [sys.executable, "-m", "ringpop_tpu.cli.simbench", "--cpu", "--only", "ring1m"],
        capture_output=True,
        text=True,
        timeout=600,
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
    )
    assert r.returncode == 0, r.stderr[-1500:]
    line = next(ln for ln in r.stdout.splitlines() if ln.startswith("{"))
    result = json.loads(line)
    assert result["bench"] == "ring1m"
    assert result["platform"] == "cpu"
    assert result["full_scale"] is False
    assert result["value"] > 0
