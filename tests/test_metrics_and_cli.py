"""Direct coverage for the metrics primitives, the accelerator probe, and a
simbench CLI smoke — the pieces everything else uses indirectly (gossip
rate tuning, bench orchestration, the committed SIMBENCH artifacts) but no
test exercised by name."""

import json
import os
import subprocess
import sys

import pytest

from ringpop_tpu.util.clock import MockClock
from ringpop_tpu.util.metrics import Histogram, Meter


def test_histogram_reservoir_and_percentiles():
    h = Histogram(sample_size=100, seed=1)
    for v in range(1, 101):
        h.update(float(v))
    assert h.count == 100
    assert h.min() == 1.0 and h.max() == 100.0
    assert abs(h.mean() - 50.5) < 1e-9
    # exact sample → interpolated percentiles land inside the data range
    p50, p99 = h.percentiles([0.5, 0.99])
    assert 49.0 <= p50 <= 52.0
    assert p99 >= 99.0
    # past sample_size the reservoir keeps a bounded uniform sample
    for v in range(101, 1001):
        h.update(float(v))
    assert h.count == 1000
    assert len(h._sample) == 100


def test_histogram_empty_is_zero():
    h = Histogram()
    assert h.percentile(0.5) == 0.0
    assert h.mean() == 0.0 and h.min() == 0.0 and h.max() == 0.0
    assert h.percentiles([0.0, 0.5, 0.99]) == [0.0, 0.0, 0.0]
    assert h.count == 0


def test_histogram_single_sample_every_percentile():
    """With one sample every percentile — including the p*(len+1) < 1 and
    >= len index edges — must return that sample, never interpolate off
    the end."""
    h = Histogram(sample_size=10, seed=3)
    h.update(42.0)
    for p in (0.0, 0.01, 0.5, 0.95, 0.99, 1.0):
        assert h.percentile(p) == 42.0, p
    assert h.min() == h.max() == h.mean() == 42.0
    assert h.count == 1


def test_histogram_reservoir_overflow_deterministic_under_seed():
    """Past sample_size the reservoir replacement is driven by the seeded
    RNG only: same seed + same update stream => identical retained sample
    (what makes committed bench artifacts reproducible); a different seed
    diverges on the same stream."""
    stream = [float(v) for v in range(500)]

    def run(seed):
        h = Histogram(sample_size=16, seed=seed)
        for v in stream:
            h.update(v)
        return list(h._sample), h.count

    s1, c1 = run(7)
    s2, c2 = run(7)
    s3, _ = run(8)
    assert s1 == s2 and c1 == c2 == 500
    assert len(s1) == 16
    assert s3 != s1  # 16-of-500 uniform samples colliding is ~impossible
    # the retained values all came from the stream
    assert set(s1) <= set(stream)


def test_meter_ewma_rate_with_mock_clock():
    clock = MockClock()
    m = Meter(clock=clock)
    assert m.rate1() == 0.0
    # 10 events/s sustained for a minute converges toward 10/s
    for _ in range(12):
        for _ in range(50):
            m.mark()
        clock.advance(5.0)
    assert m.count == 600
    assert 5.0 < m.rate1() <= 10.5


def test_meter_ewma_decays_when_idle():
    """After traffic stops, the 1-minute EWMA must decay monotonically
    toward zero under the fake clock — and an untouched meter stays at
    exactly zero however far the clock advances."""
    clock = MockClock()
    m = Meter(clock=clock)
    for _ in range(12):
        for _ in range(50):
            m.mark()
        clock.advance(5.0)
    peak = m.rate1()
    assert peak > 5.0
    rates = []
    for _ in range(24):  # two idle minutes, sampled every 5s tick
        clock.advance(5.0)
        rates.append(m.rate1())
    assert all(a >= b for a, b in zip(rates, rates[1:])), "decay not monotone"
    assert rates[0] < peak
    assert rates[-1] < 0.2 * peak  # ~2 idle minutes kill most of a 1m EWMA
    assert m.count == 600  # decay never forgets the lifetime count

    idle = Meter(clock=clock)
    clock.advance(300.0)
    assert idle.rate1() == 0.0 and idle.count == 0


def test_accel_probe_contract():
    """The probe must always return the diagnostic dict the bench artifacts
    embed, within its timeout, whatever the tunnel is doing.  (It cannot
    assert alive=True even pinned to CPU: this environment's accelerator
    site hook can initialize during jax import regardless of JAX_PLATFORMS
    and hang when the tunnel is wedged — the exact failure mode the
    subprocess probe exists to contain.)"""
    from ringpop_tpu.util.accel import probe_accelerator

    env_backup = os.environ.get("JAX_PLATFORMS")
    os.environ["JAX_PLATFORMS"] = "cpu"
    try:
        probe = probe_accelerator(timeouts_s=(45.0,))
    finally:
        if env_backup is None:
            os.environ.pop("JAX_PLATFORMS", None)
        else:
            os.environ["JAX_PLATFORMS"] = env_backup
    assert set(probe) == {"alive", "platform", "probe_s", "reason"}
    assert isinstance(probe["alive"], bool)
    assert probe["probe_s"] > 0
    if probe["alive"]:
        assert isinstance(probe["platform"], str) and probe["reason"] == "ok"
    else:
        assert probe["reason"] != "ok"


@pytest.mark.slow
def test_simbench_cli_smoke():
    """One scenario end-to-end through the CLI entry point (the artifact
    generator for SIMBENCH_r{N}.json): emits a JSON line with the
    platform/scale fields the committed artifacts carry."""
    r = subprocess.run(
        [sys.executable, "-m", "ringpop_tpu.cli.simbench", "--cpu", "--only", "ring1m"],
        capture_output=True,
        text=True,
        timeout=600,
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
    )
    assert r.returncode == 0, r.stderr[-1500:]
    line = next(ln for ln in r.stdout.splitlines() if ln.startswith("{"))
    result = json.loads(line)
    assert result["bench"] == "ring1m"
    assert result["platform"] == "cpu"
    assert result["full_scale"] is False
    assert result["value"] > 0


@pytest.mark.slow
def test_tpu_ksweep_smoke_cpu(tmp_path):
    """The watcher's measurement payload (scripts/tpu_ksweep.py) must run
    end-to-end — it only ever executes unattended in a live tunnel window,
    so a broken section would otherwise be discovered by wasting the
    window.  Tiny shapes, CPU-pinned, output redirected (KSWEEP_OUT) so a
    smoke run can never clobber real captured evidence; asserts the
    capture schema the round artifacts and PERF.md cite."""
    repo = os.path.join(os.path.dirname(__file__), "..")
    out_path = str(tmp_path / "ksweep_smoke.json")
    r = subprocess.run(
        [sys.executable, os.path.join(repo, "scripts", "tpu_ksweep.py")],
        capture_output=True,
        text=True,
        timeout=900,
        env=dict(
            os.environ,
            JAX_PLATFORMS="cpu",
            KSWEEP_PIN="cpu",
            KSWEEP_OUT=out_path,
            KSWEEP_N="2048",
            KSWEEP_KS="64",
            KSWEEP_K_HEADLINE="64",
            KSWEEP_DELTA_N="4096",
            KSWEEP_REPS="2",
        ),
        cwd=repo,
    )
    assert r.returncode == 0, r.stderr[-1500:]
    out = json.loads(r.stdout.strip().splitlines()[-1])
    assert out["platform"] == "cpu" and out["git_head"]
    tc = out["tick_cost"]["64"]
    assert tc["ms_per_tick_median"] > 0 and len(tc["block_s_reps"]) == 2
    assert out["detect_headline"]["detected"] is True
    assert out["detect_headline"]["ms_per_tick_implied"] > 0
    assert out["converge_after_detect"]["converged"] is True
    assert out["delta_1m"]["converged"] and out["delta_16m"]["converged"]
    st = out["sparse_topk"]
    assert st["bit_equal"] is True and st["sparse_ms"] > 0 and st["dense_sort_ms"] > 0
    # n=2048 sits below the static floor: the section must SAY the sparse
    # branch didn't engage, so a reader can't mistake the vacuous compare
    assert st["sparse_engaged"] is False
    assert out["ring_lookup_qps"] > 0
    # the redirected capture file carries the same record
    cap = json.load(open(out_path))
    assert cap["captured_at"] == out["captured_at"]


@pytest.mark.slow
def test_bench_fast_artifact_schema():
    """bench.py is the driver's interface: one JSON line whose schema the
    round artifacts (BENCH_r{N}.json) and BASELINE comparisons consume.
    Run it in BENCH_FAST smoke mode, forced to the CPU-only path
    (BENCH_FORCE_CPU skips the probe AND the accelerator attempt — a
    short probe timeout would merely race a live tunnel), and pin the
    fields — detection AND the literal convergence companions
    (VERDICT r3 item 3) must always ride the line."""
    repo = os.path.join(os.path.dirname(__file__), "..")
    r = subprocess.run(
        [sys.executable, os.path.join(repo, "bench.py")],
        capture_output=True,
        text=True,
        timeout=900,
        env=dict(
            os.environ,
            BENCH_FAST="1",
            BENCH_FORCE_CPU="1",
        ),
        cwd=repo,
    )
    assert r.returncode == 0, r.stderr[-1500:]
    line = next(
        ln for ln in reversed(r.stdout.strip().splitlines()) if ln.startswith("{")
    )
    out = json.loads(line)
    assert out["metric"].startswith("swim_lifecycle_detect_n")
    assert out["detected"] is True and out["ticks"] > 0
    # the literal north-star convergence companions
    assert out["converged"] is True
    assert out["converge_total_ticks"] == out["ticks"] + out["converge_extra_ticks"]
    assert out["converge_total_s"] >= out["value"]
    # scale honesty: smoke scale must not claim a 1M-baseline ratio
    assert out["vs_baseline"] is None
    assert out["vs_baseline_at_reduced_scale"] > 0
    assert out["delta_converged"] is True
    assert out["ring_lookup_qps"] > 0
    assert out["platform"] == "cpu"
    assert "probe" in out and "tpu_watcher_capture" in out
    # the driver's artifact tail is this process's stderr: the XLA:CPU AOT
    # loader's target-feature mismatch warning must never reach it — the
    # cache dir is keyed by XLA's own detected features and the parent
    # purges + reruns if the warning fires anyway (VERDICT r4 item 3)
    assert "doesn't match the machine type" not in r.stderr
    assert "could lead to execution errors such as SIGILL" not in r.stderr
