"""Monte-Carlo replica sweeps vs sequential LifecycleSim — exactness and
distribution sanity.

The MC module's claim is strong: replica b IS `LifecycleSim(seed=seeds[b])`
stepped in lockstep — same step function, same per-replica PRNG stream —
so batched results must be bit-identical to sequential runs, not merely
statistically similar.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ringpop_tpu.sim.delta import DeltaFaults
from ringpop_tpu.sim.lifecycle import LifecycleParams, LifecycleSim
from ringpop_tpu.sim.montecarlo import (
    MonteCarlo,
    detection_latency_distribution,
    init_replicas,
)

N, K = 128, 16
SEEDS = [3, 7, 11, 19]
VICTIMS = [5, 42]


def _faults():
    up = np.ones(N, bool)
    up[VICTIMS] = False
    return DeltaFaults(up=jnp.asarray(up))


def test_replicas_bit_identical_to_sequential_runs():
    params = LifecycleParams(n=N, k=K)
    faults = _faults()
    mc = MonteCarlo(params, SEEDS)
    mc.run(24, faults)

    for b, seed in enumerate(SEEDS):
        sim = LifecycleSim(n=N, k=K, seed=seed)
        sim.run(24, faults)
        for field in sim.state._fields:
            batched = np.asarray(getattr(mc.states, field))[b]
            single = np.asarray(getattr(sim.state, field))
            np.testing.assert_array_equal(batched, single, err_msg=f"{field} seed={seed}")


def test_run_until_detected_matches_sequential_ticks():
    params = LifecycleParams(n=N, k=K)
    faults = _faults()
    mc = MonteCarlo(params, SEEDS)
    ticks, detected = mc.run_until_detected(VICTIMS, faults, max_ticks=512, check_every=8)
    assert detected.all(), ticks

    for b, seed in enumerate(SEEDS):
        sim = LifecycleSim(n=N, k=K, seed=seed)
        st, ok = sim.run_until_detected(VICTIMS, faults, max_ticks=512, check_every=8)
        assert ok
        assert st == ticks[b], (seed, st, ticks[b])


def test_distribution_helper_shape():
    out = detection_latency_distribution(
        n=N, seeds=SEEDS, victims=VICTIMS, k=K, max_ticks=512
    )
    assert out["n_replicas"] == len(SEEDS)
    assert out["detected"] == len(SEEDS)
    assert out["ticks_median"] is not None
    assert out["sim_s_median"] == out["ticks_median"] * 0.2


def test_replica_axis_is_one_program():
    """The batched block is a single jitted computation over [B, ...]
    arrays (no per-replica dispatch): stepping all replicas yields batched
    leaves with a leading B axis."""
    params = LifecycleParams(n=N, k=K)
    states = init_replicas(params, SEEDS)
    assert states.learned.shape == (len(SEEDS), N, (K + 31) // 32)  # packed words
    assert states.pcount.shape == (len(SEEDS), N, K)
    assert states.key.shape[0] == len(SEEDS)


def test_huge_seed_matches_sequential_key():
    """Seeds >= 2**32 must produce exactly LifecycleSim's PRNG stream (a
    uint32 cast would wrap them to a different replica)."""
    params = LifecycleParams(n=64, k=8)
    seeds = [2**32, 2**32 + 5]
    batched = init_replicas(params, seeds)
    for b, s in enumerate(seeds):
        expect = jax.random.PRNGKey(s)
        np.testing.assert_array_equal(np.asarray(batched.key[b]), np.asarray(expect))


def test_detection_fractions_matches_per_replica():
    """The introspection API (partial progress per replica) must agree with
    per-replica detection_fraction on the equivalent single sims."""
    from ringpop_tpu.sim.lifecycle import detection_fraction

    params = LifecycleParams(n=N, k=K)
    faults = _faults()
    mc = MonteCarlo(params, SEEDS)
    mc.run(16, faults)
    got = mc.detection_fractions(VICTIMS, faults)
    assert got.shape == (len(SEEDS), len(VICTIMS))
    for b, seed in enumerate(SEEDS):
        sim = LifecycleSim(n=N, k=K, seed=seed)
        sim.run(16, faults)
        want = np.asarray(detection_fraction(sim.state, VICTIMS, faults))
        np.testing.assert_allclose(got[b], want, err_msg=str(seed))
