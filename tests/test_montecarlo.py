"""Monte-Carlo replica sweeps vs sequential LifecycleSim — exactness and
distribution sanity.

The MC module's claim is strong: replica b IS `LifecycleSim(seed=seeds[b])`
stepped in lockstep — same step function, same per-replica PRNG stream —
so batched results must be bit-identical to sequential runs, not merely
statistically similar.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ringpop_tpu.sim.delta import DeltaFaults
from ringpop_tpu.sim.lifecycle import LifecycleParams, LifecycleSim
from ringpop_tpu.sim.montecarlo import (
    MonteCarlo,
    detection_latency_distribution,
    init_replicas,
)

N, K = 128, 16
SEEDS = [3, 7, 11, 19]
VICTIMS = [5, 42]


def _faults():
    up = np.ones(N, bool)
    up[VICTIMS] = False
    return DeltaFaults(up=jnp.asarray(up))


def test_replicas_bit_identical_to_sequential_runs():
    params = LifecycleParams(n=N, k=K)
    faults = _faults()
    mc = MonteCarlo(params, SEEDS)
    mc.run(24, faults)

    for b, seed in enumerate(SEEDS):
        sim = LifecycleSim(n=N, k=K, seed=seed)
        sim.run(24, faults)
        for field in sim.state._fields:
            batched = np.asarray(getattr(mc.states, field))[b]
            single = np.asarray(getattr(sim.state, field))
            np.testing.assert_array_equal(batched, single, err_msg=f"{field} seed={seed}")


def test_run_until_detected_matches_sequential_ticks():
    params = LifecycleParams(n=N, k=K)
    faults = _faults()
    mc = MonteCarlo(params, SEEDS)
    ticks, detected = mc.run_until_detected(VICTIMS, faults, max_ticks=512, check_every=8)
    assert detected.all(), ticks

    for b, seed in enumerate(SEEDS):
        sim = LifecycleSim(n=N, k=K, seed=seed)
        st, ok = sim.run_until_detected(VICTIMS, faults, max_ticks=512, check_every=8)
        assert ok
        assert st == ticks[b], (seed, st, ticks[b])


def test_distribution_helper_shape():
    out = detection_latency_distribution(
        n=N, seeds=SEEDS, victims=VICTIMS, k=K, max_ticks=512
    )
    assert out["n_replicas"] == len(SEEDS)
    assert out["detected"] == len(SEEDS)
    assert out["ticks_median"] is not None
    assert out["sim_s_median"] == out["ticks_median"] * 0.2


def test_replica_axis_is_one_program():
    """The batched block is a single jitted computation over [B, ...]
    arrays (no per-replica dispatch): stepping all replicas yields batched
    leaves with a leading B axis."""
    params = LifecycleParams(n=N, k=K)
    states = init_replicas(params, SEEDS)
    assert states.learned.shape == (len(SEEDS), N, (K + 31) // 32)  # packed words
    assert states.pcount.shape == (len(SEEDS), N, K)
    assert states.key.shape[0] == len(SEEDS)


def test_huge_seed_matches_sequential_key():
    """Seeds >= 2**32 must produce exactly LifecycleSim's PRNG stream (a
    uint32 cast would wrap them to a different replica)."""
    params = LifecycleParams(n=64, k=8)
    seeds = [2**32, 2**32 + 5]
    batched = init_replicas(params, seeds)
    for b, s in enumerate(seeds):
        expect = jax.random.PRNGKey(s)
        np.testing.assert_array_equal(np.asarray(batched.key[b]), np.asarray(expect))


def test_detection_fractions_matches_per_replica():
    """The introspection API (partial progress per replica) must agree with
    per-replica detection_fraction on the equivalent single sims."""
    from ringpop_tpu.sim.lifecycle import detection_fraction

    params = LifecycleParams(n=N, k=K)
    faults = _faults()
    mc = MonteCarlo(params, SEEDS)
    mc.run(16, faults)
    got = mc.detection_fractions(VICTIMS, faults)
    assert got.shape == (len(SEEDS), len(VICTIMS))
    for b, seed in enumerate(SEEDS):
        sim = LifecycleSim(n=N, k=K, seed=seed)
        sim.run(16, faults)
        want = np.asarray(detection_fraction(sim.state, VICTIMS, faults))
        np.testing.assert_allclose(got[b], want, err_msg=str(seed))


def test_batched_faults_bit_identical_to_per_replica_sims():
    """Heterogeneous-scenario exactness: with a [B, N] ``up`` mask, replica
    b must be bit-identical to LifecycleSim(seed=seeds[b]) run under that
    replica's OWN fault mask — the vmapped-faults path changes which mask
    each replica sees, never the dynamics."""
    params = LifecycleParams(n=N, k=K)
    up = np.ones((len(SEEDS), N), bool)
    up[:, VICTIMS] = False
    # per-replica background churn: replica b crashes b extra nodes
    for b in range(len(SEEDS)):
        up[b, 60 : 60 + b] = False
    faults_batched = DeltaFaults(up=jnp.asarray(up))
    mc = MonteCarlo(params, SEEDS)
    mc_ticks, mc_det = mc.run_until_detected(
        VICTIMS, faults_batched, max_ticks=512, check_every=8
    )

    for b, seed in enumerate(SEEDS):
        sim = LifecycleSim(n=N, k=K, seed=seed)
        fb = DeltaFaults(up=jnp.asarray(up[b]))
        ticks, det = sim.run_until_detected(
            VICTIMS, fb, max_ticks=512, check_every=8
        )
        # (final states are not comparable here: lockstep replicas keep
        # stepping after detection while the sequential sim stops early)
        assert (ticks, det) == (int(mc_ticks[b]), bool(mc_det[b]))
    assert mc_det.all()


def test_mixed_batched_up_shared_group_vmaps():
    """up batched [B, N] + group shared [N] must vmap cleanly (per-leaf
    in_axes): the batched leaf maps, the shared leaf broadcasts."""
    params = LifecycleParams(n=N, k=K)
    up = np.ones((len(SEEDS), N), bool)
    up[:, VICTIMS] = False
    group = np.zeros(N, np.int32)
    group[N // 2 :] = -1
    faults = DeltaFaults(up=jnp.asarray(up), group=jnp.asarray(group))
    mc = MonteCarlo(params, SEEDS)
    mc.run(4, faults)  # must trace and execute without axis errors
    assert int(jax.tree.leaves(mc.states)[0].shape[0]) == len(SEEDS)


def test_churn_study_disperses():
    """The churn study must produce genuinely heterogeneous latencies (the
    homogeneous study's dispersion was PRNG noise only) and its dose-
    response rows must use null, never a numeric sentinel, for undetected
    replicas."""
    from ringpop_tpu.sim.montecarlo import detection_latency_under_churn

    out = detection_latency_under_churn(
        n=256,
        seeds=range(8),
        victims=[3, 99],
        churn_max=48,  # heavy: up to 3x the k=16 slot table
        k=16,
        max_ticks=512,
    )
    assert out["n_replicas"] == 8
    assert len(out["churn_ticks"]) == 8
    for churn, tick in out["churn_ticks"]:
        assert tick is None or tick > 0
    # replicas detecting at all must show real spread under heavy churn
    det = [t for _, t in out["churn_ticks"] if t is not None]
    assert len(det) >= 2
    assert max(det) - min(det) >= 2, out["churn_ticks"]
