"""Multi-host mesh path: the same jitted sim step over a mesh spanning OS
processes, with real cross-process collectives.

The reference's multi-machine story is N TChannel processes over TCP
(SURVEY §2.8, ``test/run-integration-tests``); the sim plane's is one
global mesh over ``jax.distributed``.  A real pod isn't available here, so
the strongest honest proof is two actual OS processes, each owning 4
virtual CPU devices, joined through the distributed runtime — the exact
code path (init_distributed → make_multihost_mesh → sharded step) a
multi-host TPU job runs, with the collectives crossing a process boundary
for real (gloo instead of DCN).
"""

import os
import socket
import subprocess
import sys

import jax
import pytest

from ringpop_tpu.parallel.multihost import make_multihost_mesh

WORKER = os.path.join(os.path.dirname(__file__), "multihost_worker.py")

# the two-process bring-up path (init_distributed in each worker) probes
# jax.distributed.is_initialized, which this container's jax 0.4.37
# lacks — the workers would die with AttributeError before any collective
# runs, so the test can only certify anything on a newer jax.  Skip with
# the reason instead of failing pre-existing (ISSUE 7 satellite).
requires_distributed_api = pytest.mark.skipif(
    not hasattr(jax.distributed, "is_initialized"),
    reason="jax.distributed.is_initialized unavailable (jax "
    f"{jax.__version__} < 0.5): multihost bring-up cannot initialize",
)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_single_host_mesh_shape():
    # in-process path: one host (this test process) → plain 2D mesh over
    # the virtual 8-device CPU backend, rumor axis defaulting to 2
    mesh = make_multihost_mesh()
    assert mesh.shape == {"node": 4, "rumor": 2}
    assert mesh.axis_names == ("node", "rumor")


@pytest.mark.slow
@requires_distributed_api
def test_two_process_mesh_runs_sharded_step():
    port = _free_port()
    env = dict(os.environ, PYTHONPATH=os.path.dirname(os.path.dirname(WORKER)))
    env.pop("JAX_PLATFORMS", None)  # worker pins its own
    procs = [
        subprocess.Popen(
            [sys.executable, WORKER, str(rank), str(port)],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
        )
        for rank in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=180)
            outs.append((p.returncode, out, err))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for rc, out, err in outs:
        assert rc == 0, f"worker failed rc={rc}\nstdout:{out}\nstderr:{err[-2000:]}"
        assert "OK" in out
