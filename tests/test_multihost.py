"""Multi-host scale-out (r14): partition/gather placement, the
process-spanning fabric step, and block-sharded snapshots — certified at
1/2/4 REAL OS processes through the actual ``jax.distributed`` bring-up.

This container's CPU backend cannot EXECUTE cross-process XLA programs
("Multiprocess computations aren't implemented"), so the certificates run
the host-bridged DCN fabric (``parallel/fabric`` +
``sim/delta_multihost``): shard-local jitted kernels, exchange windows
over TCP, reduce words allgathered — bit-identical to the single-host
``delta.step`` by construction and pinned so here.  The placement tier
(``partition.shard_put``/``host_gather``) and block-sharded orbax
checkpoints run for real across processes either way (no cross-process
computation involved).

Fast tier-1 tests drive the SAME fabric code in-process (LocalKV +
threads); the OS-process twins are slow-marked.
"""

import functools
import os
import socket
import subprocess
import sys
import threading

import jax
import numpy as np
import pytest

from ringpop_tpu.parallel.multihost import make_multihost_mesh

WORKER = os.path.join(os.path.dirname(__file__), "multihost_worker.py")

# version guard (kept per ISSUE 9): some jax builds can neither report
# distributed state (no jax.distributed.is_initialized) nor expose the
# internal global-state fallback — there the bring-up path cannot run at
# all and the process-spanning tests skip with the reason.  This
# container's 0.4.37 lacks is_initialized but HAS the fallback, so the
# tests run (the r12-era skip was about the hard is_initialized call the
# old init_distributed made; distributed_initialized removed it).
def _bringup_available() -> bool:
    if hasattr(jax.distributed, "is_initialized"):
        return True
    try:
        from jax._src import distributed  # noqa: F401

        return hasattr(distributed, "global_state")
    except Exception:
        return False


requires_distributed_api = pytest.mark.skipif(
    not _bringup_available(),
    reason="jax.distributed state is unqueryable on this jax build "
    f"({jax.__version__}): no is_initialized and no global_state fallback",
)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_single_host_mesh_shape():
    # in-process path: one host (this test process) → plain 2D mesh over
    # the virtual 8-device CPU backend, rumor axis defaulting to 2
    mesh = make_multihost_mesh()
    assert mesh.shape == {"node": 4, "rumor": 2}
    assert mesh.axis_names == ("node", "rumor")


# -- fast in-process fabric twins (tier-1) ------------------------------------


def _engine_digest(params, faults, seed, ticks):
    import jax.numpy as jnp  # noqa: F401

    from ringpop_tpu.sim.delta import init_state, step
    from ringpop_tpu.sim.telemetry import tree_digest

    st = init_state(params, seed=seed)
    stp = jax.jit(functools.partial(step, params))
    for _ in range(ticks):
        st = stp(st, faults)
    return int(tree_digest(st)), st


def _fabric_digests(params, faults, seed, ticks, nprocs, ns, codec=True,
                    schedule="cyclic", overlap=False):
    from ringpop_tpu.parallel.fabric import Fabric, LocalKV
    from ringpop_tpu.sim.delta_multihost import MultihostDelta

    kv = LocalKV()
    out = [None] * nprocs
    errs = []

    def run(rank):
        try:
            with Fabric(rank, nprocs, kv, namespace=ns, codec=codec) as fab:
                mh = MultihostDelta(params, fab, seed=seed, faults=faults,
                                    schedule=schedule, overlap=overlap)
                for _ in range(ticks):
                    mh.step()
                out[rank] = (
                    mh.state_digest(), mh.coverage(), mh.converged,
                    mh.d2h_bytes, fab.wire_stats(),
                )
        except BaseException as e:  # surfaced below
            errs.append(e)

    # daemon: a rank wedged in a socket read must fail the assertion,
    # not block interpreter shutdown afterwards
    ts = [threading.Thread(target=run, args=(r,), daemon=True) for r in range(nprocs)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(180)
    if errs:
        raise errs[0]
    assert all(o is not None for o in out), "a rank hung"
    return out


@pytest.mark.parametrize("codec", [True, False], ids=["codec-on", "codec-off"])
@pytest.mark.parametrize("nprocs", [1, 2, 4])
def test_fabric_step_bit_identical_to_engine(nprocs, codec):
    """The process-spanning step at P processes == delta.step, digest-
    exact, under the full supported fault surface (victims + loss) —
    codec-on AND codec-off (the r15 wire codec is bit-transparent by
    construction; this is the dynamic certificate)."""
    import jax.numpy as jnp

    from ringpop_tpu.sim.delta import DeltaFaults, DeltaParams

    params = DeltaParams(n=128, k=64, rng="counter")
    up = np.ones(128, bool)
    up[::9] = False
    faults = DeltaFaults(up=jnp.asarray(up), drop_rate=jnp.float32(0.1))
    ref, _ = _engine_digest(params, faults, seed=4, ticks=10)
    out = _fabric_digests(params, faults, 4, 10, nprocs, f"tw{nprocs}{int(codec)}",
                          codec=codec)
    assert {o[0] for o in out} == {ref}
    # coverage is the exact popcount fraction — identical on every rank
    assert len({o[1] for o in out}) == 1


@pytest.mark.parametrize("overlap", [False, True], ids=["sequential", "overlap"])
@pytest.mark.parametrize("schedule", ["cyclic", "swing"])
@pytest.mark.parametrize("nprocs", [2, 4])
def test_swing_and_overlap_bit_identical_to_engine(nprocs, schedule, overlap):
    """The r16 acceptance grid: every (schedule, overlap) combination at
    P in {2, 4} produces the engine digest under victims + loss, codec
    on — swing relays and cross-tick pipelining are bit-transparent.
    (The cyclic/sequential corner is the r15 path, re-pinned above.)"""
    import jax.numpy as jnp

    from ringpop_tpu.sim.delta import DeltaFaults, DeltaParams

    if schedule == "cyclic" and not overlap:
        pytest.skip("the r15 corner — covered by the codec twin above")
    params = DeltaParams(n=256, k=64, rng="counter")
    up = np.ones(256, bool)
    up[::11] = False
    faults = DeltaFaults(up=jnp.asarray(up), drop_rate=jnp.float32(0.07))
    ref, _ = _engine_digest(params, faults, seed=6, ticks=9)
    out = _fabric_digests(
        params, faults, 6, 9, nprocs, f"so{nprocs}{schedule}{int(overlap)}",
        schedule=schedule, overlap=overlap,
    )
    assert {o[0] for o in out} == {ref}
    assert len({o[1] for o in out}) == 1


def test_swing_relay_overhead_priced_at_p4_and_absent_at_p2():
    """The swing relay's extra wire bytes are REAL accounting, not
    hidden: at P=2 the swing schedule degenerates to the cyclic messages
    (identical wire totals); at P=4 relayed pieces cost strictly more
    raw bytes than the direct cyclic sends — the overhead the simbench
    artifact prices explicitly."""
    from ringpop_tpu.sim.delta import DeltaParams

    params = DeltaParams(n=256, k=64, rng="counter")
    ticks = 6
    by = {}
    for nprocs in (2, 4):
        for schedule in ("cyclic", "swing"):
            out = _fabric_digests(
                params, None, 5, ticks, nprocs, f"rp{nprocs}{schedule}",
                codec=False, schedule=schedule,
            )
            by[(nprocs, schedule)] = out[0][4]["raw_bytes_sent"]
    assert by[(2, "swing")] == by[(2, "cyclic")]
    assert by[(4, "swing")] > by[(4, "cyclic")]


def test_swing_refuses_non_power_of_two_fabric():
    from ringpop_tpu.parallel.fabric import Fabric, LocalKV
    from ringpop_tpu.sim.delta import DeltaParams
    from ringpop_tpu.sim.delta_multihost import MultihostDelta

    params = DeltaParams(n=96, k=64, rng="counter")
    kv = LocalKV()
    out = [None] * 3

    def run(rank):
        with Fabric(rank, 3, kv, namespace="swref") as fab:
            try:
                MultihostDelta(params, fab, schedule="swing")
            except ValueError as e:
                out[rank] = "power-of-two" in str(e)

    ts = [threading.Thread(target=run, args=(r,), daemon=True) for r in range(3)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(60)
    assert out == [True, True, True]


@pytest.mark.parametrize("nprocs", [1, 2, 4])
def test_exchange_d2h_is_pieces_only(nprocs):
    """r15 acceptance pin: device→host transfer per exchange leg drops
    from full-plane to pieces-only.  The pre-r15 engine materialized the
    ENTIRE local plane on host once per leg (2·ticks·block·W·4 bytes);
    the byte accounting must land strictly under that floor at P>1 and at
    ZERO at P=1 (the window is a pure device gather there)."""
    from ringpop_tpu.sim.delta import DeltaParams
    from ringpop_tpu.sim.packbits import n_words

    params = DeltaParams(n=256, k=64, rng="counter")
    ticks = 8
    out = _fabric_digests(params, None, 3, ticks, nprocs, f"d2h{nprocs}")
    block = params.n // nprocs
    plane_nbytes = block * n_words(params.k) * 4
    old_floor = 2 * ticks * plane_nbytes  # full plane, once per leg
    for digest, cov, conv, d2h, ws in out:
        if nprocs == 1:
            assert d2h == 0, d2h
        else:
            assert 0 < d2h < old_floor, (d2h, old_floor)
            # and the wire itself compressed: raw strictly above wire
            assert ws["bytes_sent"] < ws["raw_bytes_sent"]


def test_journal_carries_per_tick_deltas_and_ratio():
    """r15 observability satellite: journal records carry per-interval
    wire/raw deltas and the codec ratio (OBSERVABILITY.md schema row), so
    a journal can plot the dissemination-phase traffic wave."""
    import threading as _t

    from ringpop_tpu.parallel.fabric import Fabric, LocalKV
    from ringpop_tpu.sim.delta import DeltaParams
    from ringpop_tpu.sim.delta_multihost import MultihostDelta

    params = DeltaParams(n=128, k=64, rng="counter")
    kv = LocalKV()
    recs = [None, None]

    def run(rank):
        with Fabric(rank, 2, kv, namespace="jdelta") as fab:
            mh = MultihostDelta(params, fab, seed=1)
            per_tick = []
            for t in range(6):
                mh.step()
                # alternate light/full records: light ones must skip the
                # digest but keep coverage + the delta keys
                per_tick.append(mh.journal_record(light=t % 2 == 0))
            recs[rank] = per_tick

    ts = [_t.Thread(target=run, args=(r,), daemon=True) for r in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(120)
    assert all(r is not None for r in recs)
    for per_tick in recs:
        for t, rec in enumerate(per_tick):
            assert rec["fabric_ticks_delta"] == 1
            assert rec["fabric_wire_sent_delta"] > 0
            assert rec["fabric_codec_ratio"] >= 1.0
            assert rec["fabric_raw_sent_delta"] >= rec["fabric_wire_sent_delta"]
            assert ("digest" in rec) == (t % 2 == 1), "light/full digest mix"
            assert "coverage" in rec
            # r16 observability: schedule name + per-leg drain/overlap
            # timing ride every record (OBSERVABILITY.md schema rows)
            assert rec["schedule"] == "cyclic" and rec["overlap"] is False
            assert set(rec["fabric_leg_ms"]) == {"leg1", "leg2", "reduce"}
            assert all(v >= 0.0 for v in rec["fabric_leg_ms"].values())
            assert rec["overlap_hidden_ms"] >= 0.0
        # deltas telescope back to the cumulative counter
        assert sum(r["fabric_wire_sent_delta"] for r in per_tick) == (
            per_tick[-1]["fabric_bytes_sent"]
        )
        # something actually blocked on the wire over the run
        assert sum(r["fabric_leg_ms"]["leg1"] for r in per_tick) > 0.0


def test_state_reinstall_across_process_counts_resets_codec_epoch():
    """The r15 epoch lifecycle at the restore seam, across process
    counts: a 2-process run's state re-installed onto a 4-process fabric
    (the _install_block_state path snapshot restore uses) continues
    digest-equal to an unbroken engine run, and the XOR-delta epoch is
    forced to reset on every rank."""
    import jax.numpy as jnp  # noqa: F401

    from ringpop_tpu.parallel.fabric import Fabric, LocalKV
    from ringpop_tpu.parallel.partition import process_block
    from ringpop_tpu.sim.delta import DeltaParams, DeltaState
    from ringpop_tpu.sim.delta_multihost import MultihostDelta

    params = DeltaParams(n=256, k=64, rng="counter")
    t1, t2, seed = 7, 5, 13

    # phase 1: P=2 run, block states collected (threads share memory —
    # this is the in-process analog of the block-sharded orbax save)
    kv = LocalKV()
    blocks = [None, None]

    def run2(rank):
        with Fabric(rank, 2, kv, namespace="xp2") as fab:
            mh = MultihostDelta(params, fab, seed=seed)
            for _ in range(t1):
                mh.step()
            blocks[rank] = jax.tree.map(np.asarray, mh._as_block_state())

    ts = [threading.Thread(target=run2, args=(r,), daemon=True) for r in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(120)
    assert all(b is not None for b in blocks)
    glearned = np.concatenate([b.learned for b in blocks])
    gpcount = np.concatenate([b.pcount for b in blocks])
    gride = np.concatenate([b.ride_ok for b in blocks])

    # phase 2: re-split onto a 4-process fabric and continue
    kv4 = LocalKV()
    out = [None] * 4

    def run4(rank):
        with Fabric(rank, 4, kv4, namespace="xp4") as fab:
            mh = MultihostDelta(params, fab, seed=0)
            epoch_before = fab.codec_epoch
            lo, hi = process_block(params.n, rank, 4)
            mh._install_block_state(
                DeltaState(
                    learned=glearned[lo:hi], pcount=gpcount[lo:hi],
                    ride_ok=gride[lo:hi], tick=blocks[0].tick,
                    key=blocks[0].key,
                )
            )
            assert fab.codec_epoch > epoch_before, "epoch not reset"
            for _ in range(t2):
                mh.step()
            out[rank] = mh.state_digest()

    ts = [threading.Thread(target=run4, args=(r,), daemon=True) for r in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(120)
    assert len(set(out)) == 1 and out[0] is not None

    from ringpop_tpu.sim.delta import DeltaFaults

    ref, _ = _engine_digest(params, DeltaFaults(), seed, t1 + t2)
    assert out[0] == ref


def test_fabric_convergence_matches_engine():
    """run_until_converged through the fabric stops at the same tick with
    the same final digest as the engine's run_until_converged."""
    from ringpop_tpu.parallel.fabric import Fabric, LocalKV
    from ringpop_tpu.sim.delta import DeltaFaults, DeltaParams, init_state, run_until_converged
    from ringpop_tpu.sim.delta_multihost import MultihostDelta
    from ringpop_tpu.sim.telemetry import tree_digest

    params = DeltaParams(n=128, k=64, rng="counter")
    st = init_state(params, seed=2)
    # engine checks every tick too (check_every=1) so tick counts compare
    st, ticks, ok = run_until_converged(params, st, DeltaFaults(), max_ticks=512, check_every=1)
    assert ok
    ref = int(tree_digest(st))

    kv = LocalKV()
    out = [None, None]

    def run(rank):
        with Fabric(rank, 2, kv, namespace="conv") as fab:
            mh = MultihostDelta(params, fab, seed=2)
            t, c = mh.run_until_converged(max_ticks=512)
            out[rank] = (t, c, mh.state_digest())

    ts = [threading.Thread(target=run, args=(r,), daemon=True) for r in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(180)
    assert out[0] == out[1] and out[0] is not None
    assert out[0][0] == ticks and out[0][1] and out[0][2] == ref


def test_fabric_rejects_unsupported_faults():
    from ringpop_tpu.parallel.fabric import Fabric, LocalKV
    from ringpop_tpu.sim.delta import DeltaFaults, DeltaParams
    from ringpop_tpu.sim.delta_multihost import MultihostDelta

    params = DeltaParams(n=64, k=64, rng="counter")
    fab = Fabric(0, 1, LocalKV())
    with pytest.raises(NotImplementedError):
        MultihostDelta(
            params, fab, faults=DeltaFaults(group=np.zeros(64, np.int32))
        )
    with pytest.raises(NotImplementedError):
        MultihostDelta(
            params,
            Fabric(0, 1, LocalKV()),
            faults=DeltaFaults(drop_node=np.zeros(64, np.float32)),
        )
    # threefry params: the counter stream is what makes ranks agree
    with pytest.raises(NotImplementedError):
        MultihostDelta(DeltaParams(n=64, k=64), Fabric(0, 1, LocalKV()))


def test_plan_window_covers_and_orders():
    from ringpop_tpu.parallel.fabric import plan_window, window_pieces

    n, nprocs = 96, 4
    b = n // nprocs
    for start in (0, 1, 23, 24, 95, 71):
        pieces = window_pieces(start, b, n)
        assert sum(l for _, l in pieces) == b
        plan = plan_window(start, b, n, nprocs)
        # the plan tiles the window exactly: offsets 0..b-1 each covered once
        covered = sorted(
            (woff + i, (glo + i) % n)
            for _, glo, glen, woff in plan
            for i in range(glen)
        )
        assert [c[0] for c in covered] == list(range(b))
        # and each window slot maps to the right global row
        for woff, grow in covered:
            assert grow == (start + woff) % n


# -- OS-process twins (slow) --------------------------------------------------


def _run_workers(nprocs: int, ticks: int, env_extra=None):
    port = _free_port()
    procs = []
    for rank in range(nprocs):
        env = dict(
            os.environ,
            PYTHONPATH=os.path.dirname(os.path.dirname(WORKER)),
            JAX_COORDINATOR_ADDRESS=f"127.0.0.1:{port}",
            JAX_NUM_PROCESSES=str(nprocs),
            JAX_PROCESS_ID=str(rank),
        )
        env.pop("JAX_PLATFORMS", None)  # worker pins its own
        env.update(env_extra or {})
        procs.append(
            subprocess.Popen(
                [sys.executable, WORKER, str(ticks)],
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
                text=True,
            )
        )
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=300)
            outs.append((p.returncode, out, err))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for rc, out, err in outs:
        assert rc == 0, f"worker failed rc={rc}\nstdout:{out}\nstderr:{err[-2000:]}"
        assert "OK" in out
    return outs


def _worker_anchor(ticks: int) -> int:
    """The engine digest for the worker's fixed scenario (n=256, k=64,
    seed 9, every-16th node down, 5% loss) — computed here so the worker
    is checked against an INDEPENDENT run of the reference engine."""
    import jax.numpy as jnp

    from ringpop_tpu.sim.delta import DeltaFaults, DeltaParams

    up = np.ones(256, bool)
    up[::16] = False
    params = DeltaParams(n=256, k=64, rng="counter")
    d, _ = _engine_digest(
        params, DeltaFaults(up=jnp.asarray(up), drop_rate=jnp.float32(0.05)), 9, ticks
    )
    return d


@pytest.mark.slow
@requires_distributed_api
def test_two_process_partition_fabric_snapshot(tmp_path):
    anchor = _worker_anchor(8)
    _run_workers(
        2,
        8,
        env_extra={
            "MULTIHOST_EXPECT_DIGEST": str(anchor),
            "MULTIHOST_CKPT": str(tmp_path / "ckpt2"),
        },
    )


@pytest.mark.slow
@requires_distributed_api
def test_four_process_partition_fabric_snapshot(tmp_path):
    anchor = _worker_anchor(8)
    _run_workers(
        4,
        8,
        env_extra={
            "MULTIHOST_EXPECT_DIGEST": str(anchor),
            "MULTIHOST_CKPT": str(tmp_path / "ckpt4"),
        },
    )


@pytest.mark.slow
@requires_distributed_api
def test_cross_process_count_snapshot_restore(tmp_path):
    """2-process save -> 4-process restore -> continue, digest-equal to an
    unbroken engine run (the acceptance-criteria certificate, at test
    scale; simbench multihost16m records it at artifact scale)."""
    sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(WORKER)), "scripts"))
    from multihost_launch import launch

    import jax.numpy as jnp

    from ringpop_tpu.sim.delta import DeltaFaults, DeltaParams

    n, k, seed, t1, t2 = 512, 64, 21, 10, 6
    ckpt = str(tmp_path / "xckpt")
    base = ["-m", "ringpop_tpu.cli.multihost_bench"]
    common = ["--n", str(n), "--k", str(k), "--seed", str(seed), "--victims", "8"]
    ranks = launch(
        2, base + ["snapshot-save", *common, "--ticks", str(t1), "--path", ckpt],
        timeout_s=240,
    )
    saved = ranks[0]["records"][-1]
    ranks = launch(
        4,
        base + ["snapshot-restore", *common, "--extra-ticks", str(t2), "--path", ckpt],
        timeout_s=240,
    )
    rest = [r["records"][-1] for r in ranks]
    assert len({r["digest"] for r in rest}) == 1
    assert rest[0]["digest_at_restore"] == saved["digest"]

    # unbroken reference
    params = DeltaParams(n=n, k=k, rng="counter")
    rng = np.random.default_rng(seed + 999)
    up = np.ones(n, bool)
    up[rng.choice(n, size=8, replace=False)] = False
    ref, _ = _engine_digest(params, DeltaFaults(up=jnp.asarray(up)), seed, t1 + t2)
    assert rest[0]["digest"] == ref
