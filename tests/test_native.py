"""Native C++ hash core vs the pure-Python semantic reference.

The native library (``ringpop_tpu/native/farmhash.cpp``) must produce
bit-identical FarmHash Fingerprint32 values to ``ringpop_tpu.hashing.farm``
— wire/checksum compatibility (reference: ``swim/memberlist.go:86``,
``hashring/hashring.go:107``) depends on it.
"""

from __future__ import annotations

import random
import string

import numpy as np
import pytest

from ringpop_tpu import native
from ringpop_tpu.hashing import farm

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native library unavailable (no g++?)"
)


def _rand_strings(rng: random.Random, n: int, max_len: int = 96) -> list[str]:
    alpha = string.ascii_letters + string.digits + ".:-_/"
    return ["".join(rng.choices(alpha, k=rng.randint(0, max_len))) for _ in range(n)]


class TestScalar:
    def test_all_length_classes(self):
        # covers the 0-4 / 5-12 / 13-24 / >24 control-flow branches,
        # including multi-iteration >24 loops
        rng = random.Random(1)
        for ln in list(range(0, 64)) + [100, 1000, 4096]:
            s = bytes(rng.getrandbits(8) for _ in range(ln))
            assert native.fingerprint32(s) == farm.fingerprint32(s), ln

    def test_known_inputs(self):
        for s in ["", "a", "hello", "10.0.0.1:3000", "10.0.0.1:30000", "x" * 200]:
            assert native.fingerprint32(s.encode()) == farm.fingerprint32(s)

    def test_high_bytes_signed_char_semantics(self):
        # the <=4-byte branch uses signed char arithmetic
        for s in [b"\xff", b"\x80\xff", b"\xfe\xca\xbe", b"\xde\xad\xbe\xef"]:
            assert native.fingerprint32(s) == farm.fingerprint32(s)


class TestBatch:
    def test_batch_matches_scalar(self):
        rng = random.Random(2)
        strs = _rand_strings(rng, 300)
        out = native.fingerprint32_many(strs)
        expect = np.array([farm.fingerprint32(s) for s in strs], dtype=np.uint32)
        np.testing.assert_array_equal(out, expect)

    def test_batch_matches_numpy_batch(self):
        rng = random.Random(3)
        strs = _rand_strings(rng, 500)
        mat, lens = farm.pack_strings(strs)
        expect = farm.fingerprint32_batch(mat, lens).astype(np.uint32)
        np.testing.assert_array_equal(native.fingerprint32_many(strs), expect)

    def test_empty(self):
        assert native.fingerprint32_many([]).shape == (0,)


class TestRingTokens:
    def test_matches_reference_scheme(self):
        servers = [f"10.0.0.{i}:30{i:02d}" for i in range(8)]
        rp = 37
        toks = native.ring_tokens(servers, rp)
        assert toks.shape == (8, rp)
        for si, s in enumerate(servers):
            for r in (0, 1, 9, 10, 36):
                assert int(toks[si, r]) == farm.fingerprint32(f"{s}{r}")


class TestDispatch:
    def test_hashing_frontend_uses_same_bits(self):
        # the dispatching front-end must agree with the pure reference
        from ringpop_tpu import hashing

        rng = random.Random(4)
        for s in _rand_strings(rng, 50):
            assert hashing.fingerprint32(s) == farm.fingerprint32(s)
        strs = _rand_strings(rng, 50)
        np.testing.assert_array_equal(
            hashing.fingerprint32_many(strs),
            np.array([farm.fingerprint32(s) for s in strs], dtype=np.uint32),
        )


class TestMembershipChecksum:
    def test_matches_python_canonical_form(self):
        rng = random.Random(5)
        for n in (0, 1, 2, 7, 100):
            entries = [
                f"10.0.{rng.randint(0, 255)}.{rng.randint(0, 255)}:3000"
                f"{rng.choice(['alive', 'suspect', 'faulty', 'leave'])}"
                f"{rng.randint(1, 2**62)}"
                for _ in range(n)
            ]
            expect = farm.fingerprint32("".join(s + ";" for s in sorted(entries)))
            assert native.membership_checksum(entries) == expect, n

    def test_sort_is_bytewise_and_prefix_aware(self):
        # "a" < "a0" < "b": prefix entries must sort before their extensions
        entries = ["b", "a0", "a", "a00"]
        expect = farm.fingerprint32("".join(s + ";" for s in sorted(entries)))
        assert native.membership_checksum(entries) == expect

    def test_memberlist_uses_it(self):
        # the memberlist checksum path and gen_checksum_string must agree
        from ringpop_tpu.net.channel import LocalNetwork
        from tests.swim_utils import make_node

        node = make_node(LocalNetwork(), "10.0.0.1:3000")
        ml = node.memberlist
        for i in range(5):
            ml.make_alive(f"10.0.0.{i + 2}:3000", 1000 + i)
        assert ml.compute_checksum() == farm.fingerprint32(ml.gen_checksum_string())
        node.destroy()


class TestRingLookupNBatch:
    def _ring(self, n_servers: int, rp: int):
        from ringpop_tpu import hashring

        ring = hashring.HashRing(replica_points=rp)
        ring.add_remove_servers([f"10.0.{i // 256}.{i % 256}:3000" for i in range(n_servers)], [])
        return ring

    def test_matches_host_walk(self):
        rng = random.Random(6)
        for n_servers, rp, nwant in [(1, 3, 1), (5, 3, 3), (16, 100, 4), (7, 1, 10)]:
            ring = self._ring(n_servers, rp)
            keys = _rand_strings(rng, 200, max_len=32)
            got = ring.lookup_n_batch(keys, nwant)
            for k, row in zip(keys, got):
                assert row == ring.lookup_n(k, nwant), (n_servers, rp, nwant, k)

    def test_empty_ring_and_empty_keys(self):
        from ringpop_tpu import hashring

        ring = hashring.HashRing(replica_points=3)
        assert ring.lookup_n_batch(["k1", "k2"], 3) == [[], []]
        ring.add_server("10.0.0.1:3000")
        assert ring.lookup_n_batch([], 3) == []

    def test_python_fallback_agrees(self, monkeypatch):
        from ringpop_tpu import hashing

        ring = self._ring(9, 7)
        tokens, owners, servers = ring.token_arrays()
        rng = random.Random(7)
        keys = _rand_strings(rng, 100, max_len=24)
        hashes = hashing.fingerprint32_many(keys)
        nat = native.ring_lookup_n_batch(
            tokens.astype(np.uint32), owners, len(servers), hashes, 3
        )
        monkeypatch.setattr(hashing, "_use_native", lambda: False)
        py = hashing.ring_lookup_n_batch(
            tokens.astype(np.uint32), owners, len(servers), hashes, 3
        )
        np.testing.assert_array_equal(nat, py)
        for k, row in zip(keys, nat):
            assert [servers[int(o)] for o in row if o >= 0] == ring.lookup_n(k, 3)

    def test_custom_hashfunc_batch_agrees_with_walk(self):
        # lookup_n_batch / lookup_batch must honor a non-default hash func
        from ringpop_tpu import hashring

        def crc_ish(s):
            import zlib

            return zlib.crc32(s.encode() if isinstance(s, str) else s)

        ring = hashring.HashRing(hashfunc=crc_ish, replica_points=5)
        ring.add_remove_servers([f"10.0.0.{i}:3000" for i in range(6)], [])
        keys = [f"alpha-{i}" for i in range(50)]
        got = ring.lookup_n_batch(keys, 2)
        for k, row in zip(keys, got):
            assert row == ring.lookup_n(k, 2), k
        singles = ring.lookup_batch(keys)
        for k, s in zip(keys, singles):
            assert s == ring.lookup(k), k

    def test_nwant_zero_consistent_everywhere(self):
        from ringpop_tpu import hashing

        ring = self._ring(4, 3)
        tokens, owners, servers = ring.token_arrays()
        hashes = np.array([1, 2**31, 2**32 - 1], dtype=np.uint32)
        assert ring.lookup_n("k", 0) == []
        assert ring.lookup_n_batch(["a", "b"], 0) == [[], []]
        assert native.ring_lookup_n_batch(
            tokens.astype(np.uint32), owners, len(servers), hashes, 0
        ).shape == (3, 0)
        import unittest.mock as mock

        with mock.patch.object(hashing, "_use_native", lambda: False):
            assert hashing.ring_lookup_n_batch(
                tokens.astype(np.uint32), owners, len(servers), hashes, 0
            ).shape == (3, 0)

    def test_64bit_custom_hashfunc_tokens_masked(self):
        # tokens from a >32-bit hash func must be masked into the 32-bit
        # token space so the sorted uint32 cache stays sorted
        from ringpop_tpu import hashring

        def wide(s):
            import hashlib

            return int.from_bytes(hashlib.blake2b(
                s.encode() if isinstance(s, str) else s, digest_size=8).digest(), "big")

        ring = hashring.HashRing(hashfunc=wide, replica_points=9)
        ring.add_remove_servers([f"10.1.0.{i}:3000" for i in range(7)], [])
        tokens, _, _ = ring.token_arrays()
        assert int(tokens.max()) <= 0xFFFFFFFF
        assert (np.diff(tokens.astype(np.uint64)) >= 0).all()
        keys = [f"k{i}" for i in range(40)]
        for k, row in zip(keys, ring.lookup_n_batch(keys, 3)):
            assert row == ring.lookup_n(k, 3), k
