"""Native C++ hash core vs the pure-Python semantic reference.

The native library (``ringpop_tpu/native/farmhash.cpp``) must produce
bit-identical FarmHash Fingerprint32 values to ``ringpop_tpu.hashing.farm``
— wire/checksum compatibility (reference: ``swim/memberlist.go:86``,
``hashring/hashring.go:107``) depends on it.
"""

from __future__ import annotations

import random
import string

import numpy as np
import pytest

from ringpop_tpu import native
from ringpop_tpu.hashing import farm

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native library unavailable (no g++?)"
)


def _rand_strings(rng: random.Random, n: int, max_len: int = 96) -> list[str]:
    alpha = string.ascii_letters + string.digits + ".:-_/"
    return ["".join(rng.choices(alpha, k=rng.randint(0, max_len))) for _ in range(n)]


class TestScalar:
    def test_all_length_classes(self):
        # covers the 0-4 / 5-12 / 13-24 / >24 control-flow branches,
        # including multi-iteration >24 loops
        rng = random.Random(1)
        for ln in list(range(0, 64)) + [100, 1000, 4096]:
            s = bytes(rng.getrandbits(8) for _ in range(ln))
            assert native.fingerprint32(s) == farm.fingerprint32(s), ln

    def test_known_inputs(self):
        for s in ["", "a", "hello", "10.0.0.1:3000", "10.0.0.1:30000", "x" * 200]:
            assert native.fingerprint32(s.encode()) == farm.fingerprint32(s)

    def test_high_bytes_signed_char_semantics(self):
        # the <=4-byte branch uses signed char arithmetic
        for s in [b"\xff", b"\x80\xff", b"\xfe\xca\xbe", b"\xde\xad\xbe\xef"]:
            assert native.fingerprint32(s) == farm.fingerprint32(s)


class TestBatch:
    def test_batch_matches_scalar(self):
        rng = random.Random(2)
        strs = _rand_strings(rng, 300)
        out = native.fingerprint32_many(strs)
        expect = np.array([farm.fingerprint32(s) for s in strs], dtype=np.uint32)
        np.testing.assert_array_equal(out, expect)

    def test_batch_matches_numpy_batch(self):
        rng = random.Random(3)
        strs = _rand_strings(rng, 500)
        mat, lens = farm.pack_strings(strs)
        expect = farm.fingerprint32_batch(mat, lens).astype(np.uint32)
        np.testing.assert_array_equal(native.fingerprint32_many(strs), expect)

    def test_empty(self):
        assert native.fingerprint32_many([]).shape == (0,)


class TestRingTokens:
    def test_matches_reference_scheme(self):
        servers = [f"10.0.0.{i}:30{i:02d}" for i in range(8)]
        rp = 37
        toks = native.ring_tokens(servers, rp)
        assert toks.shape == (8, rp)
        for si, s in enumerate(servers):
            for r in (0, 1, 9, 10, 36):
                assert int(toks[si, r]) == farm.fingerprint32(f"{s}{r}")


class TestDispatch:
    def test_hashing_frontend_uses_same_bits(self):
        # the dispatching front-end must agree with the pure reference
        from ringpop_tpu import hashing

        rng = random.Random(4)
        for s in _rand_strings(rng, 50):
            assert hashing.fingerprint32(s) == farm.fingerprint32(s)
        strs = _rand_strings(rng, 50)
        np.testing.assert_array_equal(
            hashing.fingerprint32_many(strs),
            np.array([farm.fingerprint32(s) for s in strs], dtype=np.uint32),
        )
