"""r20 live-operations-plane suite (``ringpop_tpu/obs/``).

Covers the four obs pieces and their seams: the aggregating reporter +
Prometheus rendering, the LiveOps endpoint (single- and multi-rank,
cross-rank aggregation over the obs fabric, per-rank liveness), the
deterministic span tracer (key-hash sampling, header round-trip,
chain reconstruction with hop parity), the flight recorder (bounded
ring, dump format, fabric-failure + excepthook triggers), and the
hardened UDPStatsd (dead socket never raises, multi-metric datagrams).
"""

import json
import socket
import threading
import time
import urllib.request

import numpy as np
import pytest

from ringpop_tpu.obs import aggregate as agg
from ringpop_tpu.obs import trace as tracemod
from ringpop_tpu.obs.endpoint import LiveOps
from ringpop_tpu.obs.flight import FlightRecorder, git_commit
from ringpop_tpu.parallel.fabric import Fabric, FabricPeerLost, LocalKV


# -- AggregatingStats ---------------------------------------------------------


def test_aggregating_stats_counters_gauges_timings():
    st = agg.AggregatingStats()
    st.incr("a.count", 2)
    st.incr("a.count", 3)
    st.gauge("b.gauge", 1.5)
    st.gauge("b.gauge", 2.5)  # last value wins
    for v in (0.1, 0.2, 0.3):
        st.timing("c.time", v)
    snap = st.snapshot()
    assert snap["counters"]["a.count"] == 5
    assert snap["gauges"]["b.gauge"] == 2.5
    t = snap["timings"]["c.time"]
    assert t["count"] == 3 and t["min"] == 0.1 and t["max"] == 0.3
    assert abs(t["mean"] - 0.2) < 1e-9
    assert "a.count" in snap["rates_1m"]


def test_aggregating_stats_thread_safe_totals():
    st = agg.AggregatingStats()

    def pound():
        for _ in range(2000):
            st.incr("k", 1)

    ts = [threading.Thread(target=pound) for _ in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert st.snapshot()["counters"]["k"] == 8000


def test_prometheus_rendering_labels_and_aggregate():
    st = agg.AggregatingStats()
    st.incr("ringpop.sim.ping.send", 5)
    st.gauge("x-y.z", 2)
    snap = st.snapshot()
    txt = agg.render_prometheus({0: snap, 1: snap})
    assert '# TYPE ringpop_sim_ping_send counter' in txt
    assert 'ringpop_sim_ping_send{rank="0"} 5' in txt
    assert 'ringpop_sim_ping_send{rank="1"} 5' in txt
    # the unlabeled cross-rank aggregate
    assert "\nringpop_sim_ping_send 10" in txt
    # name sanitization: '-' and '.' both become '_'
    assert 'x_y_z{rank="0"} 2' in txt
    # single-rank rendering emits no aggregate duplicate
    solo = agg.render_prometheus({0: snap})
    assert "\nringpop_sim_ping_send 5\n" not in solo
    assert agg.merge_counter_totals({0: snap, 1: snap}) == {
        "ringpop.sim.ping.send": 10.0
    }


# -- Tracer -------------------------------------------------------------------


def test_tracer_sampling_is_pure_function_of_key_hash():
    records_a, records_b = [], []
    ta = tracemod.Tracer(records_a.append, sample=8)
    tb = tracemod.Tracer(records_b.append, sample=8)
    h = np.arange(256, dtype=np.uint32)
    assert (ta.sample_mask(h) == tb.sample_mask(h)).all()
    assert ta.sample_mask(h).sum() == 32
    sa = ta.begin("forward", h, salt=7)
    sb = tb.begin("forward", h, salt=7)
    assert sa.trace == sb.trace and sa.span == sb.span
    sa.finish()
    sb.finish()
    assert records_a[0]["keys"] == records_b[0]["keys"]
    assert records_a[0]["traces"] == records_b[0]["traces"]
    # an unsampled batch emits nothing at all
    assert ta.begin("forward", np.asarray([1, 2, 3], np.uint32)) is None
    assert records_a[0]["trace"] == tracemod.trace_id_of(0)


def test_tracer_header_round_trip_and_follow():
    records = []
    tr = tracemod.Tracer(records.append, sample=1, rank=3)
    sp = tr.begin("forward", np.asarray([42], np.uint32), hops=2)
    headers = {
        tracemod.TRACE_HEADER: sp.header_value(),
        "ringpop-hops": "2",
    }
    child = tr.follow(headers, "server", salt=1)
    assert child.trace == sp.trace
    assert child.record["parent"] == sp.span
    assert child.record["hops"] == 2
    # malformed/absent headers: no span, no raise
    assert tr.follow({}, "server") is None
    assert tr.follow({tracemod.TRACE_HEADER: "zzz"}, "server") is None
    assert tr.follow({tracemod.TRACE_HEADER: "12:34:56"}, "server") is None


def test_tracer_sink_failure_never_raises():
    def bad_sink(rec):
        raise RuntimeError("disk full")

    tr = tracemod.Tracer(bad_sink, sample=1)
    sp = tr.begin("forward", np.asarray([0], np.uint32))
    sp.finish()  # swallowed
    assert tr.spans_dropped == 1 and tr.spans_emitted == 0


def test_span_chain_reconstruction_orders_parent_first():
    records = []
    tr = tracemod.Tracer(records.append, sample=1)
    root = tr.begin("route", np.asarray([9], np.uint32))
    mid = tr.begin("forward", np.asarray([9], np.uint32), parent=root.span,
                   salt=1)
    leaf = tr.begin("handle", np.asarray([9], np.uint32), parent=mid.span,
                    salt=2)
    # finish out of order: chain ordering comes from parent links
    leaf.finish()
    root.finish()
    mid.finish()
    ch = tracemod.chain(records, tracemod.trace_id_of(9))
    assert [s["leg"] for s in ch] == ["route", "forward", "handle"]


# -- forwarding-plane spans (route -> forward -> handle, hop parity) ----------


def _lookup_fixture(n_servers=2, points=8):
    from ringpop_tpu.ops.ring_ops import build_ring_tokens

    servers = [f"10.31.0.{i}:3000" for i in range(n_servers)]
    toks, owns = build_ring_tokens(servers, points)
    tokens = np.asarray(toks, np.uint32)
    owners = np.asarray(owns, np.int32)

    def lookup(h, n):
        idx = np.searchsorted(tokens, np.asarray(h, np.uint32), side="left")
        idx = np.where(idx >= tokens.shape[0], 0, idx)
        return np.asarray(owners[idx], np.int32), 7

    return servers, tokens, owners, lookup


def test_forwarded_span_chain_hops_match_header():
    """The acceptance join: a forwarded key's chain reconstructs
    frontend route -> forward RPC -> receive-side handle from the
    records alone, and every forward span's ``hops`` equals the
    ``ringpop-hops`` value its downstream server/handle spans saw."""
    import asyncio

    from ringpop_tpu.forward.batch import BatchForwarder, BlockRouter
    from ringpop_tpu.net.channel import LocalChannel, LocalNetwork

    servers, tokens, owners, lookup = _lookup_fixture()
    net = LocalNetwork(seed=0)
    records = []
    tr = tracemod.Tracer(records.append, sample=1)
    for rank, addr in enumerate(servers):
        chan = LocalChannel(net, addr, app="serve")
        chan.tracer = tr
        router = BlockRouter(
            rank, len(servers), lambda: tokens, lookup, servers,
            BatchForwarder(chan, tracer=tr),
        )
        chan.register("serve", "/lookup", router.handler())
    client = LocalChannel(net, "10.31.0.99:1", app="cli")
    frontend = BlockRouter(
        0, len(servers), lambda: tokens, lookup, servers,
        BatchForwarder(client, tracer=tr),
    )
    hashes = np.asarray([0x10, 0xF0000000, 0x7F000000], np.uint32)

    loop = asyncio.new_event_loop()
    try:
        o, g = loop.run_until_complete(frontend.route(hashes, n=1))
    finally:
        loop.close()
    assert (g == 7).all()

    from ringpop_tpu.forward.batch import rank_of_hashes

    ranks = rank_of_hashes(tokens, hashes, len(servers))
    assert (ranks != 0).any(), "fixture must forward at least one key"
    for key, owner_rank in zip(hashes.tolist(), ranks.tolist()):
        ch = tracemod.chain(records, tracemod.trace_id_of(key))
        legs = [s["leg"] for s in ch]
        assert legs[0] == "route" and ch[0]["parent"] is None
        if owner_rank != 0:
            # a cross-block key must show the full forwarded chain
            assert "forward" in legs and "handle" in legs, legs
        for s in ch:
            if s["leg"] != "forward":
                continue
            kids = [k for k in ch if k.get("parent") == s["span"]
                    and k["leg"] in ("server", "handle")]
            assert kids, f"forward span {s['span']} has no downstream record"
            for k in kids:
                assert k["hops"] == s["hops"], (s, k)


# -- LiveOps ------------------------------------------------------------------


def _scrape(addr, path):
    with urllib.request.urlopen(f"http://{addr}{path}", timeout=5) as r:
        return r.read().decode()


def test_liveops_single_rank_endpoints():
    ops = LiveOps(0, 1)
    ops.stats.incr("ringpop.sim.ping.send", 4)
    ops.progress(16, 64, last_checkpoint_tick=8)
    addr = ops.serve()
    try:
        m = _scrape(addr, "/metrics")
        assert 'ringpop_sim_ping_send{rank="0"} 4' in m
        assert 'ringpop_obs_progress_ticks_done{rank="0"} 16' in m
        h = json.loads(_scrape(addr, "/healthz"))
        assert h["ok"] and h["rank"] == 0 and h["ranks"]["0"]["live"]
        p = json.loads(_scrape(addr, "/progress"))
        assert p["ranks"]["0"] == {
            "ticks_done": 16, "horizon": 64, "last_checkpoint_tick": 8,
        }
        # unknown path is a 404, not a crash
        with pytest.raises(urllib.error.HTTPError):
            _scrape(addr, "/nope")
    finally:
        ops.close()


def test_liveops_cross_rank_aggregation_and_liveness():
    kv = LocalKV()
    opses = [None, None]
    errs = [None, None]

    def worker(rank):
        try:
            ops = LiveOps(rank, 2, kv=kv, namespace="obs-agg-t")
            opses[rank] = ops
            ops.stats.incr("ringpop.sim.ping.send", 10 * (rank + 1))
            ops.progress(4 + rank, 32)
            for _ in range(3):
                ops.sync()
                time.sleep(0.01)
        except BaseException as e:  # noqa: BLE001
            errs[rank] = e

    ts = [threading.Thread(target=worker, args=(r,)) for r in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(30)
    assert errs == [None, None], errs
    addr = opses[0].serve()
    try:
        m = _scrape(addr, "/metrics")
        assert 'ringpop_sim_ping_send{rank="0"} 10' in m
        assert 'ringpop_sim_ping_send{rank="1"} 20' in m
        assert "\nringpop_sim_ping_send 30" in m
        p = json.loads(_scrape(addr, "/progress"))
        assert p["ranks"]["0"]["ticks_done"] == 4
        assert p["ranks"]["1"]["ticks_done"] == 5
        h = json.loads(_scrape(addr, "/healthz"))
        assert set(h["ranks"]) == {"0", "1"} and h["ok"]
    finally:
        for o in opses:
            o.close()


def test_liveops_sync_never_raises_after_peer_death():
    """A dead peer degrades the plane (liveness shows it) but sync on
    the survivor keeps returning — the ops plane must never take the
    sweep down."""
    kv = LocalKV()
    opses = [None, None]
    barrier = threading.Barrier(2, timeout=30)

    def worker(rank):
        ops = LiveOps(rank, 2, kv=kv, namespace="obs-death-t",
                      timeout_ms=2_000)
        opses[rank] = ops
        barrier.wait()
        ops.sync()
        if rank == 1:
            ops.close()  # rank 1 dies abruptly after one round

    ts = [threading.Thread(target=worker, args=(r,)) for r in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(30)
    ops0 = opses[0]
    deadline = time.monotonic() + 10
    # keep syncing; eventually the dead peer surfaces in health, and no
    # sync call may raise
    while time.monotonic() < deadline:
        ops0.sync()
        h = ops0.health()
        if not h["ranks"].get("1", {"live": True})["live"] or h["degraded"]:
            break
        time.sleep(0.05)
    h = ops0.health()
    assert (not h["ranks"].get("1", {"live": True})["live"]) or h["degraded"]
    ops0.close()


# -- FlightRecorder -----------------------------------------------------------


def test_flight_recorder_ring_bounds_and_dump_schema(tmp_path):
    rec = FlightRecorder(capacity=8, rank=2,
                         path=str(tmp_path / "flight.jsonl"))
    for i in range(20):
        rec.record({"kind": "block", "tick": i})
    kept = rec.records()
    assert len(kept) == 8 and kept[-1]["tick"] == 19 and kept[0]["tick"] == 12
    assert [r["flight_seq"] for r in kept] == list(range(12, 20))
    path = rec.dump("unit_test", error=RuntimeError("boom"))
    lines = [json.loads(x) for x in open(path)]
    head = lines[0]
    assert head["kind"] == "flight_header"
    assert head["reason"] == "unit_test" and "boom" in head["error"]
    assert head["rank"] == 2 and head["dropped"] == 12
    assert head["git_commit"] == git_commit()
    assert [r["tick"] for r in lines[1:]] == list(range(12, 20))
    # second dump is suppressed (first failure wins) unless forced
    assert rec.dump("again") is None
    assert rec.dump("forced", force=True) is not None


def test_flight_recorder_dumps_on_fabric_peer_lost(tmp_path):
    """Kill one rank's fabric mid-exchange: the surviving rank's
    FabricPeerLost must trigger the installed recorder's dump."""
    rec = FlightRecorder(capacity=16, rank=0,
                         path=str(tmp_path / "peer_lost.jsonl"))
    rec.install(fabric=True, excepthook=False, threads=False)
    try:
        kv = LocalKV()
        fabs = [None, None]
        ready = threading.Barrier(2, timeout=30)

        def run(rank):
            fab = Fabric(rank, 2, kv, namespace="obs-fl-t", timeout_ms=5_000)
            fabs[rank] = fab
            ready.wait()
            if rank == 1:
                time.sleep(0.1)
                fab.close()  # dies without sending
                return
            rec.record({"kind": "block", "tick": 99})
            with pytest.raises(FabricPeerLost):
                fab.exchange(7, {1: [np.ones(4, np.uint32)]}, [1])
            fab.close()

        ts = [threading.Thread(target=run, args=(r,)) for r in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(30)
        assert rec.dumped is not None
        lines = [json.loads(x) for x in open(rec.dumped)]
        assert lines[0]["reason"] == "fabric:FabricPeerLost"
        assert lines[-1]["kind"] == "block" and lines[-1]["tick"] == 99
    finally:
        rec.uninstall()


def test_flight_recorder_dumps_on_thread_exception(tmp_path):
    rec = FlightRecorder(capacity=4, rank=1,
                         path=str(tmp_path / "thread.jsonl"))
    rec.install(fabric=False, excepthook=False, threads=True)
    try:
        rec.record({"kind": "block", "tick": 5})

        def boom():
            raise ValueError("mid-sweep crash")

        t = threading.Thread(target=boom)
        t.start()
        t.join(10)
        assert rec.dumped is not None
        lines = [json.loads(x) for x in open(rec.dumped)]
        assert lines[0]["reason"] == "uncaught_thread_exception"
        assert "mid-sweep crash" in lines[0]["error"]
    finally:
        rec.uninstall()


def test_git_commit_matches_git(tmp_path):
    import subprocess

    got = git_commit()
    assert got and len(got) == 40
    try:
        want = subprocess.run(
            ["git", "rev-parse", "HEAD"], capture_output=True, text=True,
            cwd=str(tmp_path.parents[0] / ".."), timeout=10,
        )
    except (OSError, subprocess.TimeoutExpired):
        pytest.skip("git unavailable")
    # run against the repo root, not tmp_path
    import ringpop_tpu

    repo = ringpop_tpu.__file__.rsplit("/", 2)[0]
    want = subprocess.run(
        ["git", "rev-parse", "HEAD"], capture_output=True, text=True,
        cwd=repo, timeout=10,
    )
    if want.returncode != 0:
        pytest.skip("not a git checkout")
    assert got == want.stdout.strip()
    # non-repo directory: honest None, no raise
    assert git_commit(str(tmp_path)) is None


# -- UDPStatsd hardening (r20 satellite) --------------------------------------


def _udp_pair():
    from ringpop_tpu.cli.stats import UDPStatsd

    recv = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    recv.bind(("127.0.0.1", 0))
    recv.settimeout(2.0)
    return UDPStatsd(f"127.0.0.1:{recv.getsockname()[1]}"), recv


def test_udp_statsd_dead_socket_never_raises():
    udp, recv = _udp_pair()
    udp.incr("pre", 1)  # first emit flushes immediately
    assert recv.recv(256) == b"pre:1|c"
    # kill the UNDERLYING socket without telling the reporter — every
    # emit and the close must swallow the OSError
    udp._sock.close()
    udp.incr("a", 1)
    udp.gauge("b", 2.0)
    udp.timing("c", 0.5)
    udp.flush()
    udp.close()
    udp.incr("post-close", 1)  # dropped, not raised
    recv.close()


def test_udp_statsd_coalesces_multi_metric_datagrams():
    udp, recv = _udp_pair()
    udp.incr("first", 1)  # flushes alone (cold buffer)
    assert recv.recv(256) == b"first:1|c"
    # a quick burst inside the flush window coalesces; explicit flush
    # ships them as ONE newline-separated statsd multi-metric packet
    udp.incr("a", 1)
    udp.gauge("b", 2.5)
    udp.timing("c", 0.002)
    udp.flush()
    assert recv.recv(512) == b"a:1|c\nb:2.5|g\nc:2.000|ms"
    udp.close()
    recv.close()


def test_udp_statsd_datagram_size_cap_splits_packets():
    from ringpop_tpu.cli.stats import UDPStatsd

    recv = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    recv.bind(("127.0.0.1", 0))
    recv.settimeout(2.0)
    udp = UDPStatsd(
        f"127.0.0.1:{recv.getsockname()[1]}", max_datagram=24, flush_s=3600
    )
    udp.incr("warm", 1)  # cold-buffer flush
    assert recv.recv(64) == b"warm:1|c"
    for i in range(4):
        udp.incr(f"key{i}", i)  # 8 bytes each; cap 24 → flush mid-burst
    udp.close()  # final flush
    got = [recv.recv(64) for _ in range(2)]
    lines = [ln for g in got for ln in g.split(b"\n")]
    assert lines == [b"key0:0|c", b"key1:1|c", b"key2:2|c", b"key3:3|c"]
    for g in got:
        assert len(g) <= 24
    recv.close()


def test_span_ids_distinct_across_route_and_quorum_paths_default_salts():
    """Review fix (r20): the same key forwarded to the same dest at the
    same hop level through TWO upstream paths (frontend route, then a
    quorum wave) must emit fully distinct span ids at DEFAULT salts —
    the parent rides the id — and both chains keep their own
    downstream server/handle records."""
    import asyncio

    from ringpop_tpu.forward.batch import (
        BatchForwarder,
        BlockRouter,
        QuorumReader,
    )
    from ringpop_tpu.net.channel import LocalChannel, LocalNetwork

    servers, tokens, owners, lookup = _lookup_fixture()
    net = LocalNetwork(seed=0)
    records = []
    tr = tracemod.Tracer(records.append, sample=1)
    for rank, addr in enumerate(servers):
        chan = LocalChannel(net, addr, app="serve")
        chan.tracer = tr
        router = BlockRouter(
            rank, 2, lambda: tokens, lookup, servers,
            BatchForwarder(chan, tracer=tr),
        )
        chan.register("serve", "/lookup", router.handler())
    client = LocalChannel(net, "10.31.0.98:1", app="cli")
    cfwd = BatchForwarder(client, tracer=tr)
    frontend = BlockRouter(0, 2, lambda: tokens, lookup, servers, cfwd)
    reader = QuorumReader(cfwd, servers, r=2)
    key = np.asarray([0xF0000000], np.uint32)  # remote-owned

    loop = asyncio.new_event_loop()
    try:
        loop.run_until_complete(frontend.route(key, n=1))
        loop.run_until_complete(
            reader.quorum_wave(tokens, owners, 2, key)  # default salt
        )
    finally:
        loop.close()
    ids = [s["span"] for s in records]
    assert len(ids) == len(set(ids)), (
        f"span id collision: {[(s['leg'], s['span']) for s in records]}"
    )
    ch = tracemod.chain(records, tracemod.trace_id_of(0xF0000000))
    forwards = [s for s in ch if s["leg"] == "forward"]
    assert len(forwards) >= 2  # the route path AND a quorum read
    for s in forwards:
        kids = [k for k in ch if k.get("parent") == s["span"]
                and k["leg"] in ("server", "handle")]
        assert kids and all(k["hops"] == s["hops"] for k in kids)


def test_obs_fabric_failures_do_not_burn_the_flight_dump(tmp_path):
    """Review fix (r20): a ``notify_failures=False`` fabric (the obs
    plane's side channel) must NOT trigger the global failure hooks —
    its peer losses/timeouts are routine rank skew, and the flight
    recorder's once-per-process dump belongs to ENGINE fabric failures."""
    rec = FlightRecorder(capacity=8, rank=0,
                         path=str(tmp_path / "quiet.jsonl"))
    rec.install(fabric=True, excepthook=False, threads=False)
    try:
        kv = LocalKV()
        ready = threading.Barrier(2, timeout=30)

        def run(rank):
            fab = Fabric(rank, 2, kv, namespace="obs-quiet-t",
                         timeout_ms=5_000, notify_failures=False)
            ready.wait()
            if rank == 1:
                time.sleep(0.1)
                fab.close()
                return
            with pytest.raises(FabricPeerLost):
                fab.exchange(9, {1: [np.ones(2, np.uint32)]}, [1])
            fab.close()

        ts = [threading.Thread(target=run, args=(r,)) for r in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(30)
        assert rec.dumped is None, "quiet fabric burned the flight dump"
    finally:
        rec.uninstall()
