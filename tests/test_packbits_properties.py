"""Randomized property sweeps for the bit-packed plane primitives.

Every sim-engine boolean plane rides `sim/packbits.py` (learned, ride_ok
and every mask derived from them), and the round-3 bit-identity claim —
packed engines compute exactly what the bool-plane engines computed —
reduces to these word-level primitives agreeing with their boolean
definitions.  The goldens pin whole trajectories; these sweeps pin each
primitive in isolation across shapes the engines actually use (word-tail
Ks, non-power-of-two Ns), in the repo's seeded-random style
(`test_member_properties.py`), not hand-picked tables.
"""

from __future__ import annotations

import numpy as np
import pytest

from ringpop_tpu.sim.packbits import (
    WORD,
    and_reduce_rows,
    bit_column,
    check_rumor_shardable,
    n_words,
    or_reduce_rows,
    pack_bool,
    row_mask,
    set_bit,
    unpack_bits,
)

SHAPES = [(1, 1), (3, 8), (7, 32), (5, 33), (16, 64), (9, 95), (33, 129)]


def _rand_plane(rng, n, k):
    return rng.random((n, k)) < 0.5


@pytest.mark.parametrize("n,k", SHAPES)
def test_pack_unpack_roundtrip_and_zero_tail(n, k):
    rng = np.random.default_rng(n * 1000 + k)
    for _ in range(5):
        b = _rand_plane(rng, n, k)
        p = np.asarray(pack_bool(b))
        assert p.shape == (n, n_words(k)) and p.dtype == np.uint32
        assert np.array_equal(np.asarray(unpack_bits(p, k)), b)
        # tail bits past k in the last word are zero by construction — the
        # engines' word-level ANY/ALL reductions depend on it
        tail = n_words(k) * WORD - k
        if tail:
            assert not (p[:, -1] >> np.uint32(WORD - tail)).any()


@pytest.mark.parametrize("n,k", SHAPES)
def test_word_ops_are_boolean_ops(n, k):
    """The packed engines combine planes with &, |, ~row_mask — each must
    equal the boolean-plane op bit for bit."""
    rng = np.random.default_rng(n * 7919 + k)
    a, b = _rand_plane(rng, n, k), _rand_plane(rng, n, k)
    pa, pb = pack_bool(a), pack_bool(b)
    assert np.array_equal(np.asarray(unpack_bits(pa | pb, k)), a | b)
    assert np.array_equal(np.asarray(unpack_bits(pa & pb, k)), a & b)
    rows = rng.random(n) < 0.5
    gated = np.asarray(unpack_bits(pa & row_mask(rows), k))
    assert np.array_equal(gated, a & rows[:, None])


@pytest.mark.parametrize("n,k", SHAPES)
def test_row_reduces_match_numpy(n, k):
    rng = np.random.default_rng(n * 104729 + k)
    b = _rand_plane(rng, n, k)
    p = pack_bool(b)
    assert np.array_equal(
        np.asarray(unpack_bits(or_reduce_rows(p)[None, :], k))[0], b.any(axis=0)
    )
    assert np.array_equal(
        np.asarray(unpack_bits(and_reduce_rows(p)[None, :], k))[0], b.all(axis=0)
    )


@pytest.mark.parametrize("n,k", [(5, 33), (16, 64), (9, 95)])
def test_bit_column_scalar_and_batched(n, k):
    rng = np.random.default_rng(n * 31 + k)
    b = _rand_plane(rng, n, k)
    p = pack_bool(b)
    for j in (0, 1, 31, 32, k - 1):
        assert np.array_equal(np.asarray(bit_column(p, j)), b[:, j])
    js = rng.integers(0, k, size=n)
    assert np.array_equal(
        np.asarray(bit_column(p, js)), b[np.arange(n), js]
    )


@pytest.mark.parametrize("n,k", [(8, 33), (32, 64), (11, 95)])
def test_set_bit_matches_loop_reference(n, k):
    """set_bit with distinct (row, slot) pairs == the per-pair loop; rows
    out of [0, n) are dropped (the engines clip-and-gate this way)."""
    rng = np.random.default_rng(n * 613 + k)
    b = _rand_plane(rng, n, k)
    m = min(n, k)
    rows = rng.permutation(n)[:m].astype(np.int64)
    rows[0] = n + 3  # one out-of-range row must be dropped
    slots = rng.permutation(k)[:m]
    on = rng.random(m) < 0.7
    out = np.asarray(
        unpack_bits(set_bit(pack_bool(b), rows, slots, on), k)
    )
    want = b.copy()
    for r, s, o in zip(rows, slots, on):
        if o and 0 <= r < n:
            want[r, s] = True
    assert np.array_equal(out, want)


def test_shard_rule_accepts_exactly_multiples():
    for shards in (2, 4, 8):
        for mult in (1, 2, 3):
            check_rumor_shardable(WORD * shards * mult, shards)
    for k, shards in ((WORD, 2), (WORD * 3, 2), (WORD * 2 + 1, 2), (WORD * 2, 4)):
        with pytest.raises(ValueError):
            check_rumor_shardable(k, shards)
    check_rumor_shardable(17, 1)  # unsharded rumor axis accepts any k
