"""The canonical per-leaf partition table (parallel/partition, r14).

Pins: (1) the legacy per-engine sharding helpers DERIVE from the one rule
table (bit-for-bit the shardings they always produced); (2) the
node-block ownership rule matches where the multihost meshes actually
place rows; (3) shard_put/host_gather round-trip exactly; (4) the digest
partial sums compose to ``telemetry.tree_digest`` at any block split —
the property every multi-process certificate rides on.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from ringpop_tpu.parallel import partition
from ringpop_tpu.parallel.mesh import delta_shardings, make_mesh
from ringpop_tpu.parallel.multihost import make_multihost_mesh
from ringpop_tpu.sim import lifecycle, telemetry
from ringpop_tpu.sim.delta import DeltaFaults, DeltaParams, DeltaState, init_state


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(8)


def test_delta_shardings_derive_from_table(mesh):
    ds = delta_shardings(mesh)
    want = dict(
        learned=P("node", "rumor"), pcount=P("node", "rumor"),
        ride_ok=P("node", "rumor"), tick=P(), key=P(),
    )
    for f, spec in want.items():
        assert getattr(ds, f) == NamedSharding(mesh, spec), f


def test_lifecycle_shardings_derive_from_table(mesh):
    ls = lifecycle.state_shardings(mesh, k=64)
    want = dict(
        r_subject=P("rumor"), r_inc=P("rumor"), r_status=P("rumor"),
        r_deadline=P("rumor"), learned=P("node", "rumor"),
        pcount=P("node", "rumor"), ride_ok=P("node", "rumor"),
        base_status=P("node"), base_inc=P("node"), base_present=P("node"),
        base_pending=P("node"), base_deadline=P("node"), self_inc=P("node"),
        tick=P(), key=P(),
    )
    for f, spec in want.items():
        assert getattr(ls, f) == NamedSharding(mesh, spec), f


def test_fleet_shardings_prepend_batch_axis(mesh):
    from ringpop_tpu.sim.montecarlo import fleet_state_shardings

    fs = fleet_state_shardings(mesh, k=64)
    ls = lifecycle.state_shardings(mesh, k=64)
    for f in lifecycle.LifecycleState._fields:
        assert getattr(fs, f) == NamedSharding(
            mesh, P(None, *getattr(ls, f).spec)
        ), f


def test_fault_and_plan_and_telemetry_leaves_match_table():
    from ringpop_tpu.sim import chaos

    f = DeltaFaults(
        up=np.ones(8, bool), group=np.zeros(8, np.int32),
        drop_rate=np.float32(0.1), drop_node=np.zeros(8, np.float32),
        reach=np.ones((2, 2), bool),
    )
    sp = partition.partition_spec(f)
    assert sp.up == P("node") and sp.group == P("node") and sp.drop_node == P("node")
    assert sp.drop_rate == P() and sp.reach == P()  # tiny / scalar: replicated

    plan = chaos.FaultPlan(
        base_up=np.ones(8, bool), crash_tick=np.zeros(8, np.int32),
        flap_period=np.zeros(8, np.int32), part_from=np.int32(0),
    )
    ps = partition.partition_spec(plan)
    assert ps.base_up == P("node") and ps.crash_tick == P("node")
    assert ps.flap_period == P("node") and ps.part_from == P()

    tel = telemetry.zeros(lifecycle.LifecycleParams(n=64, k=64))
    ts = partition.partition_spec(tel)
    assert ts.pings == P("node") and ts.piggybacked == P("node", "rumor")
    assert ts.timer_fires == P("rumor") and ts.base_timer_fires == P("node")
    assert ts.decl_alive == P() and ts.heal_attempts == P() and ts.ticks == P()


def test_process_block_matches_mesh_placement():
    """The contiguous-equal-block ownership rule == where a
    make_multihost_mesh node axis actually places rows (single-process
    here, so every device belongs to rank 0 — the per-device row ranges
    must tile process_block(n, 0, 1) in device order, and the block
    arithmetic must agree with devices_indices_map splits)."""
    mesh = make_multihost_mesh(rumor_shards=1)
    n = 64
    sh = NamedSharding(mesh, P("node"))
    dmap = sh.devices_indices_map((n,))
    starts = sorted(
        (0 if s[0].start is None else s[0].start) for s in dmap.values()
    )
    node_shards = mesh.shape["node"]
    assert starts == [i * (n // node_shards) for i in range(node_shards)]
    # the process-level rule is the same split at process granularity
    assert partition.process_block(n, 0, 1) == (0, n)
    assert partition.process_block(n, 1, 4) == (16, 32)
    with pytest.raises(ValueError):
        partition.process_block(10, 0, 4)  # divisibility is the contract


def test_shard_put_host_gather_round_trip():
    params = DeltaParams(n=64, k=64, rng="counter")
    state = init_state(params, seed=3)
    host = jax.tree.map(np.asarray, state)
    mesh = make_multihost_mesh()  # 4x2 over the virtual 8 devices
    g = partition.shard_put(host, mesh, global_n=params.n)
    assert g.learned.sharding == NamedSharding(mesh, P("node", "rumor"))
    assert g.tick.sharding.is_fully_replicated
    back = partition.host_gather(g)
    for f, a, b in zip(state._fields, jax.tree.leaves(host), jax.tree.leaves(back)):
        assert np.array_equal(np.asarray(a), np.asarray(b)), f
    # the placed state is USABLE: one sharded step runs on it
    from ringpop_tpu.parallel.mesh import sharded_delta_step

    out = sharded_delta_step(params, mesh)(g)
    assert int(out.tick) == int(state.tick) + 1


@pytest.mark.parametrize("nblocks", [2, 4])
def test_leaf_partials_compose_to_tree_digest(nblocks):
    params = DeltaParams(n=64, k=64, rng="counter")
    state = init_state(params, seed=7)
    full = int(telemetry.tree_digest(state))
    b = params.n // nblocks
    parts = []
    for r in range(nblocks):
        lo = r * b
        blk = state._replace(
            learned=state.learned[lo : lo + b],
            pcount=state.pcount[lo : lo + b],
            ride_ok=state.ride_ok[lo : lo + b],
        )
        parts.append(
            np.asarray(
                partition.leaf_partial_sums(blk, lo=lo, include_replicated=r == 0)
            )
        )
    assert partition.combine_leaf_partials(parts) == full


def test_unknown_leaf_replicates():
    # a leaf no rule names must land replicated, not crash or mis-shard
    tree = {"brand_new_gauge": np.zeros((4, 4), np.int32)}
    assert partition.partition_spec(tree)["brand_new_gauge"] == P()
