"""Tests for the partition-invariant counter RNG (``sim/prng.py``) and its
engine wiring (``rng="counter"``).

Three layers:

* generator-level: lane values are a pure function of (seed, tick, draw,
  lane) — identical across 1/2/4/8-way node meshes AND rumor meshes, with
  ZERO collectives in the censused partitioned HLO;
* statistical smoke: chi-square uniformity of 1M draws (the generator is
  SplitMix-class — murmur3 fmix32 rounds over a Weyl walk — so this is a
  wiring check, not a PRNG audit);
* engine-level: the r8 acceptance bar — a sharded lifecycle run over the
  4×2 virtual mesh is bit-identical to its unsharded twin under
  ``rng="counter"``, state AND telemetry counters (the threefry peer draw
  diverged on exactly this pairing; see test_mesh_budget.py's history
  note), and likewise for the delta engine.
"""

from __future__ import annotations

import dataclasses
import functools
import importlib.util
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ringpop_tpu.sim import delta, lifecycle, prng
from ringpop_tpu.sim.delta import DeltaFaults

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _census_collectives(lowered, tmp_path) -> int:
    spec = importlib.util.spec_from_file_location(
        "profile_mesh", os.path.join(_REPO, "scripts", "profile_mesh.py")
    )
    pm = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(pm)
    p = tmp_path / "prng_hlo.txt"
    p.write_text(lowered.compile().as_text())
    census = pm.parse_collectives(str(p))
    return sum(len(v) for v in census["computations"].values())


# -- generator level ---------------------------------------------------------


def test_lane_values_mesh_invariant_and_collective_free(tmp_path):
    """The same (seed, tick, draw, lane) coordinates produce the same
    values on every mesh factorization — and the sharded draw program
    compiles with ZERO collectives (the property the threefry draws
    lack, and the reason the peer-choice phase's 12 MB/chip all-reduce
    existed at all)."""
    n = 1 << 12
    key = jax.random.PRNGKey(7)
    seed = prng.fold_key(key)
    lanes = jnp.arange(n, dtype=jnp.int32)

    def draw(lane):
        return prng.draw_randint(seed, jnp.int32(3), prng.D_PEER, lane, 0, n)

    ref = np.asarray(jax.jit(draw)(lanes))
    devices = jax.devices("cpu")
    for node_shards, rumor_shards in ((1, 1), (2, 1), (4, 2), (8, 1), (2, 4)):
        ndev = node_shards * rumor_shards
        mesh = Mesh(
            np.asarray(devices[:ndev]).reshape(node_shards, rumor_shards),
            ("node", "rumor"),
        )
        sh = NamedSharding(mesh, P("node"))
        jdraw = jax.jit(draw, in_shardings=(sh,), out_shardings=sh)
        lowered = jdraw.lower(jax.device_put(lanes, sh))
        assert _census_collectives(lowered, tmp_path) == 0, (
            f"counter draw emits collectives on a {node_shards}x{rumor_shards} mesh"
        )
        out = np.asarray(jdraw(jax.device_put(lanes, sh)))
        assert (out == ref).all(), (
            f"lane values diverged on a {node_shards}x{rumor_shards} mesh"
        )


def test_draw_sites_and_ticks_are_distinct_streams():
    seed = prng.fold_key(jax.random.PRNGKey(0))
    lanes = jnp.arange(4096, dtype=jnp.int32)
    a = np.asarray(prng.draw_u32(seed, 1, prng.D_TARGET, lanes))
    b = np.asarray(prng.draw_u32(seed, 1, prng.D_DROP, lanes))
    c = np.asarray(prng.draw_u32(seed, 2, prng.D_TARGET, lanes))
    d = np.asarray(prng.draw_u32(prng.fold_key(jax.random.PRNGKey(1)), 1, prng.D_TARGET, lanes))
    for other, what in ((b, "draw site"), (c, "tick"), (d, "seed")):
        frac_equal = (a == other).mean()
        assert frac_equal < 0.01, f"streams nearly identical across {what}"


def test_fold_key_distinct_and_vmappable():
    keys = jax.vmap(jax.random.PRNGKey)(jnp.arange(64))
    seeds = np.asarray(jax.vmap(prng.fold_key)(keys))
    assert len(set(seeds.tolist())) == 64, "fold_key collided on 64 keys"


def test_uniform_range_and_randint_bounds():
    seed = prng.fold_key(jax.random.PRNGKey(3))
    lanes = jnp.arange(1 << 16, dtype=jnp.int32)
    u = np.asarray(prng.draw_uniform(seed, 5, prng.D_DROP, lanes))
    assert (0.0 <= u).all() and (u < 1.0).all()
    r = np.asarray(prng.draw_randint(seed, 5, prng.D_TARGET, lanes, 7, 93))
    assert r.min() >= 7 and r.max() < 93
    with pytest.raises(ValueError):
        prng.draw_randint(seed, 5, prng.D_TARGET, lanes, 5, 5)


def test_uniformity_chi_square_1m():
    """Chi-square smoke over 1M draws in 256 equiprobable bins: statistic
    ~ chi2(255), mean 255, sd ~22.6.  The acceptance window is ±6 sd —
    deterministic draws, so this either always passes or flags a real
    generator regression (e.g. a dropped mix round)."""
    seed = prng.fold_key(jax.random.PRNGKey(11))
    lanes = jnp.arange(1_000_000, dtype=jnp.int32)
    u32 = np.asarray(prng.draw_u32(seed, 17, prng.D_PEER + 1, lanes))
    counts = np.bincount((u32 >> 24).astype(np.int64), minlength=256)
    expected = len(lanes) / 256
    chi2 = ((counts - expected) ** 2 / expected).sum()
    assert 120 < chi2 < 392, f"chi2={chi2:.1f} outside [120, 392] for df=255"
    # and the modulo-reduced randint too (the engines draw targets this way)
    r = np.asarray(prng.draw_randint(seed, 17, prng.D_TARGET, lanes, 0, 1000))
    counts = np.bincount(r, minlength=1000)
    expected = len(lanes) / 1000
    chi2 = ((counts - expected) ** 2 / expected).sum()
    # df=999: mean 999, sd ~44.7; same ±6-sd window
    assert 750 < chi2 < 1270, f"chi2={chi2:.1f} outside [750, 1270] for df=999"


# -- engine level ------------------------------------------------------------


def _mesh_4x2():
    return Mesh(np.asarray(jax.devices("cpu")[:8]).reshape(4, 2), ("node", "rumor"))


def test_lifecycle_sharded_run_bit_equals_unsharded_counter():
    """The r8 acceptance pairing on the 4×2 virtual mesh: a full sharded
    lifecycle run (shift exchange, faults, drop, heal, telemetry) under
    ``rng="counter"`` + the shard-local exchange is bit-identical — every
    state leaf and every telemetry counter — to the unsharded program."""
    from ringpop_tpu.sim import telemetry

    mesh = _mesh_4x2()
    n, k = 8192, 64
    plain = lifecycle.LifecycleParams(n=n, k=k, suspect_ticks=6, rng="counter")
    sharded = dataclasses.replace(plain, exchange_mesh=mesh)
    up = np.ones(n, bool)
    up[::128] = False
    faults = DeltaFaults(up=jnp.asarray(up), drop_rate=0.02)
    ref_blk = jax.jit(functools.partial(lifecycle._run_block, plain), static_argnames="ticks")
    sm_blk = jax.jit(functools.partial(lifecycle._run_block, sharded), static_argnames="ticks")
    ref_s, ref_t = ref_blk(
        lifecycle.init_state(plain, seed=5), faults, ticks=8,
        telemetry=telemetry.zeros(plain),
    )
    sstate = jax.tree.map(
        jax.device_put, lifecycle.init_state(sharded, seed=5),
        lifecycle.state_shardings(mesh, k=k),
    )
    sh_s, sh_t = sm_blk(sstate, faults, ticks=8, telemetry=telemetry.zeros(sharded))
    for a, b in zip(jax.tree.leaves(ref_s), jax.tree.leaves(sh_s)):
        assert bool((np.asarray(a) == np.asarray(b)).all())
    ref_rec, _ = telemetry.fetch(ref_t, ref_s, faults)
    sh_rec, _ = telemetry.fetch(sh_t, sh_s, faults)
    ref_rec, sh_rec = jax.device_get((ref_rec, sh_rec))
    for key in ref_rec:
        assert np.asarray(ref_rec[key]) == np.asarray(sh_rec[key]), key


def test_delta_sharded_run_bit_equals_unsharded_counter():
    from ringpop_tpu.parallel.mesh import delta_shardings

    mesh = _mesh_4x2()
    n, k = 8192, 64
    plain = delta.DeltaParams(n=n, k=k, rng="counter")
    sharded = dataclasses.replace(plain, exchange_mesh=mesh)
    up = np.ones(n, bool)
    up[::64] = False
    faults = DeltaFaults(up=jnp.asarray(up), drop_rate=0.03)
    ref_step = jax.jit(functools.partial(delta.step, plain))
    sm_step = jax.jit(functools.partial(delta.step, sharded))
    ref = delta.init_state(plain, seed=9)
    sh = jax.tree.map(jax.device_put, delta.init_state(sharded, seed=9), delta_shardings(mesh))
    for _ in range(8):
        ref = ref_step(ref, faults)
        sh = sm_step(sh, faults)
    for a, b in zip(jax.tree.leaves(ref), jax.tree.leaves(sh)):
        assert bool((np.asarray(a) == np.asarray(b)).all())


def test_counter_run_reaches_detection():
    """The counter stream drives the protocol end to end: victims get
    detected and the run converges — i.e. the new draws are protocol-
    adequate, not just well-distributed."""
    sim = lifecycle.LifecycleSim(n=512, k=32, seed=1, suspect_ticks=5, rng="counter")
    up = np.ones(512, bool)
    victims = [17, 130, 400]
    up[victims] = False
    faults = DeltaFaults(up=jnp.asarray(up))
    ticks, ok = sim.run_until_detected(victims, faults, max_ticks=2000, check_every=16)
    assert ok, f"counter-RNG run failed to detect in {ticks} ticks"


def test_rng_families_differ_but_key_is_stable():
    """Sanity on the wiring: counter and threefry draw different
    trajectories (they are different generators), and the counter run
    never consumes its key leaf (the stream is (seed, tick)-addressed)."""
    n = 256
    base = lifecycle.LifecycleParams(n=n, k=16, suspect_ticks=4)
    counter = dataclasses.replace(base, rng="counter")
    up = np.ones(n, bool)
    up[13] = False
    faults = DeltaFaults(up=jnp.asarray(up))
    s0 = lifecycle.init_state(base, seed=2)
    a, b = s0, s0
    step_t = jax.jit(functools.partial(lifecycle.step, base))
    step_c = jax.jit(functools.partial(lifecycle.step, counter))
    # both detect the crash, but through different draws — somewhere along
    # the dissemination the learned planes (who heard the rumor when) must
    # differ (comparing only the END state would be vacuous: once the
    # rumor folds into the base the plane is all-zero under both streams)
    diverged = False
    for _ in range(12):
        a = step_t(a, faults)
        b = step_c(b, faults)
        diverged |= not np.array_equal(np.asarray(a.learned), np.asarray(b.learned))
    assert diverged, "counter and threefry drew identical trajectories?"
    assert np.array_equal(np.asarray(b.key), np.asarray(s0.key)), "counter run split its key"
    assert not np.array_equal(np.asarray(a.key), np.asarray(s0.key)), "threefry run kept its key"


def test_unknown_rng_family_raises():
    params = lifecycle.LifecycleParams(n=64, k=16, rng="philox")
    with pytest.raises(ValueError, match="rng"):
        lifecycle.step(params, lifecycle.init_state(dataclasses.replace(params, rng="threefry"), seed=0))
    dparams = delta.DeltaParams(n=64, k=32, rng="philox")
    with pytest.raises(ValueError, match="rng"):
        delta.step(dparams, delta.init_state(dataclasses.replace(dparams, rng="threefry"), seed=0))
