"""Dynamic plane-3 (racecheck) tests.

In-process tests install/uninstall the instrumentation around tiny
single-thread scenarios — the dynamic lock-order graph and the
held-while-blocking capture are deterministic there (held stacks are
thread-local; acquiring a→b then b→a sequentially records both edge
directions without ever realizing the deadlock).  The non-vacuity legs
drive ``scripts/race_harness.py --probe`` in a subprocess: the clean
probe must hold the r22 count-before-respond invariant, the mutant
probe must be caught.
"""

from __future__ import annotations

import contextlib
import json
import os
import subprocess
import sys
import threading
import time

import pytest

from ringpop_tpu.analysis import racecheck

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_HARNESS = os.path.join(_REPO, "scripts", "race_harness.py")


@contextlib.contextmanager
def installed(**kw):
    rec = racecheck.install(**kw)
    try:
        yield rec
    finally:
        racecheck.uninstall()


def test_install_is_exclusive_and_current():
    assert racecheck.current() is None
    with installed(seed=1) as rec:
        assert racecheck.current() is rec
        with pytest.raises(RuntimeError):
            racecheck.install()
    assert racecheck.current() is None


def test_lock_graph_edges_and_cycle():
    with installed(seed=1) as rec:
        a = threading.Lock()
        b = threading.Lock()
        with a:
            with b:
                pass
        with b:
            with a:
                pass
    rep = rec.report()
    assert len(rep["lock_sites"]) == 2
    assert all(n == 1 for n in rep["lock_sites"].values())
    assert rep["acquire_count"] == 4
    # both edge directions present -> exactly one elementary cycle
    edges = {(e[0], e[1]) for e in rep["edges"]}
    assert len(edges) == 2
    assert {(y, x) for (x, y) in edges} == edges
    assert len(rep["cycles"]) == 1
    assert sorted(rep["cycles"][0]) == sorted(rep["lock_sites"])


def test_same_site_locks_share_a_node_and_make_no_edge():
    with installed(seed=1) as rec:
        locks = [threading.Lock() for _ in range(2)]  # one allocation site
        with locks[0]:
            with locks[1]:
                pass
    rep = rec.report()
    assert len(rep["lock_sites"]) == 1
    assert list(rep["lock_sites"].values()) == [2]
    assert rep["edges"] == []  # same-site edge is reentry, not an order
    assert rep["cycles"] == []


def test_nested_acquisition_without_inversion_has_no_cycle():
    with installed(seed=1) as rec:
        a = threading.Lock()
        b = threading.Lock()
        for _ in range(3):
            with a:
                with b:
                    pass
    rep = rec.report()
    assert len(rep["edges"]) == 1
    assert rep["edges"][0][2] == 3  # edge weight counts occurrences
    assert rep["cycles"] == []


def test_sleep_under_lock_is_a_block_event():
    with installed(seed=1) as rec:
        lock = threading.Lock()
        time.sleep(0)  # not held: no event
        with lock:
            time.sleep(0)
    events = rec.report()["block_events"]
    assert len(events) == 1
    assert events[0]["op"] == "time.sleep"
    assert len(events[0]["held"]) == 1


def test_condition_wait_excludes_its_own_lock():
    with installed(seed=1) as rec:
        cond = threading.Condition()  # default lock: the patched RLock
        outer = threading.Lock()
        with cond:
            cond.wait(timeout=0.01)  # only own lock held: NOT an event
        with outer:
            with cond:
                cond.wait(timeout=0.01)  # outer held across the wait: event
    events = rec.report()["block_events"]
    assert len(events) == 1
    assert events[0]["op"] == "Condition.wait"
    assert len(events[0]["held"]) == 1  # the outer lock, not cond's own


def test_event_and_queue_pick_up_instrumentation():
    import queue

    with installed(seed=1) as rec:
        ev = threading.Event()
        assert isinstance(ev._cond._lock, racecheck._InstrumentedLock)
        assert type(ev._cond).__name__ == "_InstrumentedCondition"
        q = queue.Queue()
        assert isinstance(q.mutex, racecheck._InstrumentedLock)
        q.put(1)
        assert q.get() == 1
        ev.set()
        assert ev.wait(timeout=1)
    assert rec.report()["acquire_count"] > 0


def test_rlock_reentry_and_condition_protocol():
    with installed(seed=1) as rec:
        r = threading.RLock()
        with r:
            with r:  # reentry: one logical hold
                pass
        cond = threading.Condition(threading.RLock())
        hits = []

        def waiter():
            with cond:
                while not hits:
                    cond.wait(timeout=5)

        t = threading.Thread(target=waiter)
        t.start()
        time.sleep(0.05)
        with cond:
            hits.append(1)
            cond.notify()
        t.join(timeout=5)
        assert not t.is_alive()
    # reentry registered a single acquisition for the RLock hold pair
    assert rec.report()["acquire_count"] >= 1


def test_perturbation_stream_is_seed_deterministic():
    def stream(seed, n=200):
        rec = racecheck.Recorder(
            seed=seed, perturb=True, p=0.3, sleep_range_us=(1, 2))
        out = []
        for _ in range(n):
            rec.maybe_perturb()
            out.append(rec.perturb_count)
        return out

    s3a, s3b, s4 = stream(3), stream(3), stream(4)
    assert s3a == s3b  # same seed -> identical decision stream
    assert s3a != s4  # different seed -> different stream
    assert s3a[-1] > 0  # and perturbations actually fired


def test_uninstall_restores_stdlib_and_orphans_keep_working():
    with installed(seed=1):
        orphan = threading.Lock()
    assert threading.Lock is racecheck._ORIG_LOCK
    assert threading.RLock is racecheck._ORIG_RLOCK
    assert threading.Condition is racecheck._ORIG_CONDITION
    assert time.sleep is racecheck._ORIG_SLEEP
    # the wrapper outlives its install window: private real inner lock
    with orphan:
        assert orphan.locked()
    assert not orphan.locked()


def test_report_dump_roundtrip(tmp_path):
    with installed(seed=9, perturb=True, p=0.5, sleep_range_us=(1, 2)) as rec:
        a = threading.Lock()
        with a:
            pass
    out = tmp_path / "race.json"
    rec.dump(str(out))
    rep = json.loads(out.read_text())
    assert rep["seed"] == 9 and rep["perturb"] is True and rep["p"] == 0.5
    assert rep["acquire_count"] == 1


# -- the non-vacuity probe pair (subprocess legs) ------------------------------


def _run_probe(mode: str, seed: int = 1):
    proc = subprocess.run(
        [sys.executable, _HARNESS, "--probe", mode, "--seeds", str(seed)],
        capture_output=True, text=True, cwd=_REPO, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    doc = None
    for line in proc.stdout.splitlines():
        try:
            doc = json.loads(line)
            break
        except ValueError:
            continue
    return proc, doc


def test_clean_probe_holds_invariant_at_head():
    proc, doc = _run_probe("clean")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert doc is not None and doc["violations"] == 0
    assert doc["calls"] == 150


def test_seeded_mutant_is_caught():
    # the r22 write-then-count mutant MUST be observed under perturbation;
    # rc 3 here means the harness went vacuous
    proc, doc = _run_probe("mutant")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert doc is not None and doc["violations"] > 0


def test_harness_rejects_unknown_smoke():
    proc = subprocess.run(
        [sys.executable, _HARNESS, "--smokes", "bogus", "--skip-mutant"],
        capture_output=True, text=True, cwd=_REPO, timeout=60,
    )
    assert proc.returncode == 2
    assert "unknown smoke" in proc.stderr
