"""Property suite for the device ring-lookup ops (serve-the-ring PR).

Randomized rings with DUPLICATE and ADJACENT token hashes plus keys that
hash exactly onto a token: for every (n, window) configuration —
including windows forced small enough that the window-overflow rescue
must fire — the device result must equal the host bisect walk.  The
padded (capacity + traced count) serve-tier variants are pinned to the
same oracle and to the exact-size ops.

Also pins the dtype edge this PR fixed: int64/int32 key hashes (a caller
forgetting the uint32 cast; ``jnp.asarray`` truncates int64 to int32
under disabled x64) used to compare SIGNED against the uint32 tokens,
silently mis-routing every key in the top half of the hash space.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from ringpop_tpu.hashring import HashRing
from ringpop_tpu.hashing import fingerprint32
from ringpop_tpu.ops.ring_ops import (
    PAD_TOKEN,
    _lookup_n_window,
    _lookup_n_window_padded,
    pad_ring_arrays,
    ring_lookup,
    ring_lookup_n,
    ring_lookup_n_padded,
    ring_lookup_padded,
)


def _walk_oracle(tokens, owners, h, n, num_servers):
    """The host ring walk (hashring._lookup_n_hash semantics) on raw
    arrays: first n unique owners at token >= h with wraparound."""
    t = len(tokens)
    if t == 0 or n <= 0:
        return [-1] * max(n, 0)
    start = int(np.searchsorted(tokens, np.uint32(h), side="left"))
    out, seen = [], set()
    for i in range(t):
        o = int(owners[(start + i) % t])
        if o not in seen:
            seen.add(o)
            out.append(o)
            if len(out) == min(n, num_servers):
                break
    return out + [-1] * (n - len(out))


def _adversarial_ring(rng, t, num_servers):
    """(tokens uint32, owners int32) with long same-owner runs (forces the
    rescue), duplicate tokens, and composite (token, owner) sort order —
    the host ring's collision-resolution order."""
    owners = np.sort(rng.integers(0, num_servers, size=t)).astype(np.int32)
    rng.shuffle(owners[: t // 2])  # half shuffled, half one long run
    vals = (
        rng.integers(0, max(t // 3, 2), size=t).astype(np.uint64)
        * np.uint64(int(rng.integers(1, 2**26)))
    ) & np.uint64(0xFFFFFFFF)
    tokens = np.sort(vals).astype(np.uint32)
    comp = tokens.astype(np.uint64) << np.uint64(32) | owners.astype(np.uint64)
    order = np.argsort(comp, kind="stable")
    return tokens[order], owners[order]


def _probe_keys(rng, tokens):
    """Random keys + every token exactly + token±1 + hash-space extremes."""
    return np.unique(
        np.concatenate(
            [
                rng.integers(0, 2**32, size=24, dtype=np.uint32),
                tokens,
                tokens + np.uint32(1),
                tokens - np.uint32(1),
                np.array([0, 1, 2**32 - 1, 2**32 - 2], dtype=np.uint32),
            ]
        ).astype(np.uint32)
    )


def test_lookup_n_matches_walk_oracle_adversarial():
    rng = np.random.default_rng(41)
    for trial in range(6):
        t = int(rng.integers(3, 48))
        ns = int(rng.integers(1, 7))
        tokens, owners = _adversarial_ring(rng, t, ns)
        keys = _probe_keys(rng, tokens)
        jt, jo, jk = jnp.asarray(tokens), jnp.asarray(owners), jnp.asarray(keys)
        got1 = np.asarray(ring_lookup(jt, jo, jk))
        for n in (1, 2, ns, ns + 2):
            got = np.asarray(ring_lookup_n(jt, jo, jk, n, ns))
            for i, h in enumerate(keys.tolist()):
                want = _walk_oracle(tokens, owners, h, n, ns)
                assert list(got[i]) == want, (trial, n, i, h)
                if n >= 1:
                    assert got[i][0] == got1[i] or want[0] == got1[i]


def test_lookup_n_every_window_config():
    """Drive the windowed scan DIRECTLY at every window size 1..t: any w
    that reports all keys satisfied must agree with the oracle prefix,
    and w == t (the overflow fallback) must be exact for every key."""
    rng = np.random.default_rng(42)
    t, ns = 24, 4
    tokens, owners = _adversarial_ring(rng, t, ns)
    keys = _probe_keys(rng, tokens)
    jt, jo, jk = jnp.asarray(tokens), jnp.asarray(owners), jnp.asarray(keys)
    for n in (1, 2, 4, 6):
        need = min(n, ns)
        for w in (1, 2, 3, n, t // 2, t):
            w = max(1, min(w, t))
            out, found = _lookup_n_window(jt, jo, jk, n, w)
            out, found = np.asarray(out), np.asarray(found)
            for i, h in enumerate(keys.tolist()):
                want = _walk_oracle(tokens, owners, h, n, ns)
                if w == t or found[i] >= need:
                    assert list(out[i]) == want, (n, w, i, h)
                else:
                    # a partial window may only report a PREFIX of the walk
                    k = int(found[i])
                    assert list(out[i][:k]) == want[:k], (n, w, i, h)


@pytest.mark.parametrize("extra_cap", [0, 3, 17])
def test_padded_variants_match_exact_and_oracle(extra_cap):
    rng = np.random.default_rng(43)
    for trial in range(4):
        t = int(rng.integers(1, 40))
        ns = int(rng.integers(1, 6))
        tokens, owners = _adversarial_ring(rng, t, ns)
        keys = _probe_keys(rng, tokens)
        cap = t + extra_cap
        pt, po, count = pad_ring_arrays(tokens, owners, cap)
        jt, jo = jnp.asarray(pt), jnp.asarray(po)
        jc = jnp.asarray(count, jnp.int32)
        jk = jnp.asarray(keys)
        got1 = np.asarray(ring_lookup_padded(jt, jo, jc, jk))
        exact1 = np.asarray(
            ring_lookup(jnp.asarray(tokens), jnp.asarray(owners), jk)
        )
        assert np.array_equal(got1, exact1)
        for n in (1, 2, ns + 1):
            got = np.asarray(
                ring_lookup_n_padded(jt, jo, jc, jnp.asarray(ns, jnp.int32), jk, n)
            )
            for i, h in enumerate(keys.tolist()):
                assert list(got[i]) == _walk_oracle(tokens, owners, h, n, ns), (
                    trial, extra_cap, n, i, h,
                )


def test_padded_window_mod_count_not_capacity():
    """Walk positions must advance mod COUNT: with capacity > count, a
    key landing near the end of the live region must wrap back to live
    token 0, never into the PAD_TOKEN tail."""
    tokens = np.array([10, 20, 30], np.uint32)
    owners = np.array([0, 1, 2], np.int32)
    pt, po, count = pad_ring_arrays(tokens, owners, 8)
    out, found = _lookup_n_window_padded(
        jnp.asarray(pt), jnp.asarray(po), jnp.asarray(count, jnp.int32),
        jnp.asarray([25], jnp.uint32), 3, 4,
    )
    assert list(np.asarray(out)[0]) == [2, 0, 1]
    assert int(np.asarray(found)[0]) == 3


def test_padded_empty_ring_answers_minus_one():
    pt, po, count = pad_ring_arrays(
        np.empty(0, np.uint32), np.empty(0, np.int32), 4
    )
    keys = jnp.asarray([0, 1, 2**32 - 1], jnp.uint32)
    got = np.asarray(
        ring_lookup_padded(
            jnp.asarray(pt), jnp.asarray(po), jnp.asarray(count, jnp.int32), keys
        )
    )
    assert (got == -1).all()
    gotn = np.asarray(
        ring_lookup_n_padded(
            jnp.asarray(pt), jnp.asarray(po), jnp.asarray(count, jnp.int32),
            jnp.asarray(0, jnp.int32), keys, 2,
        )
    )
    assert (gotn == -1).all()


def test_key_hashing_exactly_pad_token_value():
    """A key hashing to 0xFFFFFFFF (== PAD_TOKEN): with a live token of
    that exact value, side='left' must find the real token; without one,
    the lookup must wrap to live token 0 — never answer a pad owner."""
    with_hit = np.array([5, PAD_TOKEN], np.uint32)
    owners = np.array([0, 1], np.int32)
    pt, po, count = pad_ring_arrays(with_hit, owners, 6)
    got = np.asarray(
        ring_lookup_padded(
            jnp.asarray(pt), jnp.asarray(po), jnp.asarray(count, jnp.int32),
            jnp.asarray([PAD_TOKEN], jnp.uint32),
        )
    )
    assert got[0] == 1
    without = np.array([5, 9], np.uint32)
    pt, po, count = pad_ring_arrays(without, owners, 6)
    got = np.asarray(
        ring_lookup_padded(
            jnp.asarray(pt), jnp.asarray(po), jnp.asarray(count, jnp.int32),
            jnp.asarray([PAD_TOKEN], jnp.uint32),
        )
    )
    assert got[0] == 0  # wrapped to the first live token


def test_signed_dtype_hashes_route_like_uint32():
    """The fixed edge: hashes arriving int64/int32 with values >= 2**31
    must route exactly like their uint32 reinterpretation (previously the
    signed comparison answered the wrap owner for the top half of the
    hash space)."""
    tokens = np.array([100, 2**31 + 5, 2**32 - 10], np.uint32)
    owners = np.array([0, 1, 2], np.int32)
    jt, jo = jnp.asarray(tokens), jnp.asarray(owners)
    h_int64 = np.array([2**31 + 5, 2**31 + 6, 2**32 - 5, 50], dtype=np.int64)
    h_u32 = h_int64.astype(np.uint32)
    a = np.asarray(ring_lookup(jt, jo, jnp.asarray(h_int64)))
    b = np.asarray(ring_lookup(jt, jo, jnp.asarray(h_u32)))
    assert np.array_equal(a, b)
    # 2**31+5 and +6 land on/after token[1]; 2**32-5 exceeds every token
    # (wraps to owner 0); 50 lands before token[0]
    assert list(b) == [1, 2, 0, 0]
    an = np.asarray(ring_lookup_n(jt, jo, jnp.asarray(h_int64), 2, 3))
    bn = np.asarray(ring_lookup_n(jt, jo, jnp.asarray(h_u32), 2, 3))
    assert np.array_equal(an, bn)
    # padded flavors too (the serve tier's resident programs)
    pt, po, count = pad_ring_arrays(tokens, owners, 5)
    pa = np.asarray(
        ring_lookup_padded(
            jnp.asarray(pt), jnp.asarray(po), jnp.asarray(count, jnp.int32),
            jnp.asarray(h_int64),
        )
    )
    assert np.array_equal(pa, b)


# -- the serve-path LookupN satellites (r17): the fused dispatch and the
# host-mirror fast lane vs the LookupNUniqueAt walk oracle -------------------


def _device_ring(tokens, owners, extra_cap=5, gen=7):
    from ringpop_tpu.serve.state import device_ring

    return device_ring(tokens, owners, tokens.shape[0] + extra_cap, gen=gen)


def test_serve_fused_lookup_n_matches_walk_oracle_adversarial():
    """The fused serve dispatch (owners + generation, one device array)
    must equal ring_lookup_n, host_lookup_n AND the inline walk oracle on
    adversarial rings — duplicate/adjacent tokens, long same-owner runs,
    wraparound keys."""
    from ringpop_tpu.ops.ring_ops import host_lookup_n
    from ringpop_tpu.serve.state import serve_lookup_n_fused

    rng = np.random.default_rng(44)
    for trial in range(4):
        t = int(rng.integers(3, 40))
        ns = int(rng.integers(1, 6))
        tokens, owners = _adversarial_ring(rng, t, ns)
        keys = _probe_keys(rng, tokens)
        ring = _device_ring(tokens, owners, extra_cap=int(rng.integers(0, 9)))
        for n in (1, 2, ns, ns + 2):
            fused = np.asarray(
                serve_lookup_n_fused(ring, ns, jnp.asarray(keys), n)
            )
            assert fused[-1] == 7  # the generation rides the same transfer
            got = fused[:-1].reshape(keys.shape[0], n)
            exact = np.asarray(
                ring_lookup_n(jnp.asarray(tokens), jnp.asarray(owners),
                              jnp.asarray(keys), n, ns)
            )
            host = host_lookup_n(tokens, owners, keys, n, ns)
            assert np.array_equal(got, exact), (trial, n)
            assert np.array_equal(got, host), (trial, n)
            for i, h in enumerate(keys.tolist()):
                assert list(got[i]) == _walk_oracle(tokens, owners, h, n, ns)


def test_serve_fused_r_exceeds_live_count():
    """R > live server count: the fused dispatch pads with -1 after every
    unique owner, exactly like the host walk."""
    from ringpop_tpu.ops.ring_ops import host_lookup_n
    from ringpop_tpu.serve.state import serve_lookup_n_fused

    tokens = np.array([10, 20, 30, 40], np.uint32)
    owners = np.array([0, 1, 0, 1], np.int32)
    ring = _device_ring(tokens, owners)
    keys = np.array([5, 25, 45], np.uint32)
    fused = np.asarray(serve_lookup_n_fused(ring, 2, jnp.asarray(keys), 5))
    got = fused[:-1].reshape(3, 5)
    assert np.array_equal(got, host_lookup_n(tokens, owners, keys, 5, 2))
    assert (got[:, 2:] == -1).all()  # only 2 unique owners exist


def test_serve_fused_all_but_one_owner_dead():
    """All-but-one owner dead: after removing every other server from a
    live RingStore, every preference list collapses to [survivor, -1...],
    for every key including wraparound — through the serve path."""
    from ringpop_tpu.ops.ring_ops import host_lookup_n
    from ringpop_tpu.serve.state import RingStore, serve_lookup_n_fused

    servers = [f"10.9.1.{i}:3000" for i in range(6)]
    store = RingStore(servers, replica_points=8)
    store.update(remove=servers[1:])
    ring, gen, ns = store.snapshot()
    assert ns == 1
    keys = np.array([0, 1, 2**31, 2**32 - 1, 1234567], np.uint32)
    fused = np.asarray(serve_lookup_n_fused(ring, ns, jnp.asarray(keys), 3))
    got = fused[:-1].reshape(keys.shape[0], 3)
    assert fused[-1] == gen
    assert (got[:, 0] == 0).all()  # the lone survivor renumbers to id 0
    assert (got[:, 1:] == -1).all()
    ht, ho, hg, hns = store.snapshot_host()
    assert np.array_equal(got, host_lookup_n(ht, ho, keys, 3, hns))


def test_serve_fused_pad_token_valued_keys():
    """Keys hashing to PAD_TOKEN exactly: with a live token of that value
    the walk starts there; without one it wraps to live token 0 — the
    fused path must never answer a pad owner."""
    from ringpop_tpu.ops.ring_ops import host_lookup_n
    from ringpop_tpu.serve.state import serve_lookup_n_fused

    keys = np.array([PAD_TOKEN, PAD_TOKEN - 1], np.uint32)
    with_hit = np.array([5, 900, PAD_TOKEN], np.uint32)
    owners = np.array([0, 1, 2], np.int32)
    ring = _device_ring(with_hit, owners)
    fused = np.asarray(serve_lookup_n_fused(ring, 3, jnp.asarray(keys), 2))
    got = fused[:-1].reshape(2, 2)
    assert np.array_equal(got, host_lookup_n(with_hit, owners, keys, 2, 3))
    assert list(got[0]) == [2, 0]  # real token == PAD_TOKEN wins side=left
    without = np.array([5, 900], np.uint32)
    ring2 = _device_ring(without, owners[:2])
    fused2 = np.asarray(serve_lookup_n_fused(ring2, 2, jnp.asarray(keys), 2))
    got2 = fused2[:-1].reshape(2, 2)
    assert np.array_equal(got2, host_lookup_n(without, owners[:2], keys, 2, 2))
    assert list(got2[0]) == [0, 1]  # wrapped to live token 0, never a pad


def test_serve_fused_forced_window_overflow_rescue():
    """A ring dominated by one owner's long run forces the first window
    (4n) to find fewer than the required unique owners — the fused path's
    host loop must double the window and still answer exactly."""
    from ringpop_tpu.ops.ring_ops import host_lookup_n
    from ringpop_tpu.serve.state import serve_lookup_n_fused

    t = 96
    owners = np.zeros(t, np.int32)
    owners[-3:] = [1, 2, 3]  # the other owners hide past a 93-token run
    tokens = (np.arange(t, dtype=np.uint32) * np.uint32(1000) + np.uint32(7))
    ring = _device_ring(tokens, owners, extra_cap=11)
    keys = np.array([0, 5, 500, 93_000], np.uint32)
    n = 4
    fused = np.asarray(serve_lookup_n_fused(ring, 4, jnp.asarray(keys), n))
    got = fused[:-1].reshape(keys.shape[0], n)
    assert np.array_equal(got, host_lookup_n(tokens, owners, keys, n, 4))
    for i, h in enumerate(keys.tolist()):
        assert list(got[i]) == _walk_oracle(tokens, owners, h, n, 4)


def test_host_lookup_n_oracle_matches_inline_walk():
    """host_lookup_n (the batched host oracle the serve fast lane answers
    from) is itself pinned to the reference walk on adversarial rings."""
    from ringpop_tpu.ops.ring_ops import host_lookup_n

    rng = np.random.default_rng(45)
    for _ in range(4):
        t = int(rng.integers(2, 32))
        ns = int(rng.integers(1, 5))
        tokens, owners = _adversarial_ring(rng, t, ns)
        keys = _probe_keys(rng, tokens)
        for n in (1, 3, ns + 1):
            got = host_lookup_n(tokens, owners, keys, n, ns)
            for i, h in enumerate(keys.tolist()):
                assert list(got[i]) == _walk_oracle(tokens, owners, h, n, ns)
    # empty ring / n=0 degenerate shapes
    empty = host_lookup_n(np.empty(0, np.uint32), np.empty(0, np.int32),
                          np.array([1], np.uint32), 2, 0)
    assert empty.shape == (1, 2) and (empty == -1).all()


def test_lookup_matches_live_hash_ring():
    """End to end: the padded device ring built from a real HashRing's
    token arrays answers every key like ring.lookup (including keys
    crafted to collide with vnode tokens)."""
    servers = [f"10.0.0.{i}:3000" for i in range(12)]
    ring = HashRing(replica_points=20)
    ring.add_remove_servers(servers, [])
    toks, owns, slist = ring.token_arrays()
    pt, po, count = pad_ring_arrays(
        toks.astype(np.uint32), owns.astype(np.int32), toks.shape[0] + 13
    )
    keys = [f"user:{i}" for i in range(300)]
    hashes = np.array(
        [fingerprint32(k.encode()) for k in keys], dtype=np.uint32
    )
    got = np.asarray(
        ring_lookup_padded(
            jnp.asarray(pt), jnp.asarray(po), jnp.asarray(count, jnp.int32),
            jnp.asarray(hashes),
        )
    )
    want = [slist.index(ring.lookup(k)) for k in keys]
    assert list(got) == want
