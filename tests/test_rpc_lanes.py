"""r23 latency-tiered RPC plane: inline completion (``call_sync``),
spin-then-park readers, small-frame coalescing, the same-host shm frame
lane, and the per-lane ``TransportLedger`` dimension.

The tier invariants under test:
- a blocked sync caller is fulfilled ON the reader thread (zero loop
  hops) and a timed-out one is NEVER fulfilled twice;
- sticky link failure fails inline waiters exactly like loop waiters
  (same typed error, promptly — not a timeout);
- coalesced frames arrive in enqueue order across flush boundaries, and
  ``urgent`` cuts the window;
- the shm lane moves bit-identical bodies (TCP stays negotiation +
  fallback), and per-lane ledger sums reconcile exactly with the
  per-class totals.
"""

import asyncio
import struct
import threading
import time

import pytest

import bench
from ringpop_tpu.net.channel import (
    CallTimeoutError,
    PeerUnreachableError,
    RemoteError,
    TCPChannel,
)
from ringpop_tpu.parallel.fabric import (
    TAG_RPC_REQ,
    RpcEndpoint,
    TransportLedger,
    _HDR,
)


def _run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


def _sync_pair(codec="msgpack", **kw):
    """A listen_sync echo server + a client channel (caller closes both)."""
    server = TCPChannel(app="srv", codec=codec, **kw)

    def echo(body, headers):
        return body

    def boom(body, headers):
        raise ValueError("handler boom")

    server.register("t", "/echo", echo)
    server.register("t", "/boom", boom)
    addr = server.listen_sync("127.0.0.1", 0)
    client = TCPChannel(app="cli", codec=codec, **kw)
    return server, client, addr


# -- inline completion --------------------------------------------------------


def test_call_sync_roundtrip_counts_inline_completion():
    server, client, addr = _sync_pair()
    try:
        body = {"x": 7, "s": "hello"}
        assert client.call_sync(addr, "t", "/echo", body, timeout=10) == body
        st = client.ledger.stats()
        rpc = st["classes"]["rpc"]
        assert rpc["inline_completions"] >= 1
        # the completion is attributed to the lane that delivered it
        assert sum(
            r["inline_completions"] for r in rpc["lanes"].values()
        ) == rpc["inline_completions"]
    finally:
        client.close_sync()
        server.close_sync()


def test_call_sync_remote_error_and_missing_handler():
    # the missing-handler error reply needs the loop path — run the
    # server in async mode so both reply shapes cross the sync caller
    server = TCPChannel(app="srv", codec="json")

    def boom(body, headers):
        raise ValueError("handler boom")

    server.register("t", "/boom", boom)

    loop = asyncio.new_event_loop()
    t = threading.Thread(target=loop.run_forever, daemon=True)
    t.start()
    addr = asyncio.run_coroutine_threadsafe(
        server.listen("127.0.0.1", 0), loop
    ).result(5)
    client = TCPChannel(app="cli", codec="json")
    try:
        with pytest.raises(RemoteError, match="handler boom"):
            client.call_sync(addr, "t", "/boom", {}, timeout=10)
        with pytest.raises(RemoteError, match="no handler"):
            client.call_sync(addr, "t", "/nope", {}, timeout=10)
    finally:
        client.close_sync()
        asyncio.run_coroutine_threadsafe(server.close(), loop).result(5)
        loop.call_soon_threadsafe(loop.stop)
        t.join(timeout=5)
        loop.close()


def test_call_sync_timeout_forgets_rid():
    """A timed-out sync caller raises CallTimeoutError and its late
    reply is dropped by the demux — never delivered, never doubled."""
    server = TCPChannel(app="srv", codec="json")
    release = threading.Event()

    def slow(body, headers):
        release.wait(10)
        return {"late": True}

    server.register("t", "/slow", slow)
    addr = server.listen_sync("127.0.0.1", 0)
    client = TCPChannel(app="cli", codec="json")
    try:
        with pytest.raises(CallTimeoutError):
            client.call_sync(addr, "t", "/slow", {}, timeout=0.05)
        release.set()
        # the link survives the late reply and serves the next call
        server.register("t", "/echo", lambda b, h: b)
        assert client.call_sync(addr, "t", "/echo", {"k": 1}, timeout=10) == {
            "k": 1
        }
    finally:
        release.set()
        client.close_sync()
        server.close_sync()


def test_inline_completion_concurrent_timeout_race():
    """N threads race tiny timeouts against reader-thread fulfillment:
    every call either returns the correct echo or raises
    CallTimeoutError — and no reply callback ever fires twice (pinned
    at the fabric layer below with per-rid counters)."""
    server, client, addr = _sync_pair(codec="json")
    errs = []

    def caller(i):
        for j in range(25):
            body = {"i": i, "j": j}
            # alternate a realistic timeout with one tight enough to
            # lose the race sometimes on a loaded container
            timeout = 10 if j % 2 == 0 else 0.002
            try:
                res = client.call_sync(addr, "t", "/echo", body, timeout=timeout)
                if res != body:
                    errs.append(f"wrong echo {res!r} for {body!r}")
            except CallTimeoutError:
                pass  # the tight-timeout side losing is expected
            except Exception as e:  # pragma: no cover - the assertion below
                errs.append(f"{type(e).__name__}: {e}")

    threads = [threading.Thread(target=caller, args=(i,)) for i in range(6)]
    try:
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert not errs, errs[:5]
        # the link is still healthy after the storm
        assert client.call_sync(addr, "t", "/echo", {"ok": 1}, timeout=10) == {
            "ok": 1
        }
    finally:
        client.close_sync()
        server.close_sync()


def test_reply_callback_never_fires_twice_under_forget_race():
    """Fabric-level pin: per-rid callbacks racing ``forget`` against
    response delivery fire AT MOST once (a forgotten rid may fire zero
    times; a kept one exactly once)."""
    fired: dict = {}
    lock = threading.Lock()

    def handler(link, rid, payload):
        link.respond(rid, bytes(payload))

    server = RpcEndpoint(handler)
    client = RpcEndpoint()
    try:
        addr = server.listen("127.0.0.1", 0)
        link = client.connect(addr)
        rids = []
        for i in range(200):
            rid = link.alloc_id()
            rids.append(rid)

            def cb(payload, lane, rid=rid):
                with lock:
                    fired[rid] = fired.get(rid, 0) + 1

            link.request(rid, b"x" * 8, cb)
            if i % 3 == 0:
                link.forget(rid)  # races the in-flight response
        deadline = time.time() + 10
        kept = [r for i, r in enumerate(rids) if i % 3 != 0]
        while time.time() < deadline:
            with lock:
                if all(fired.get(r, 0) == 1 for r in kept):
                    break
            time.sleep(0.01)
        with lock:
            assert all(fired.get(r, 0) == 1 for r in kept)
            assert all(n <= 1 for n in fired.values()), fired
    finally:
        client.close()
        server.close()


def test_sticky_failure_fails_sync_waiters_like_loop_waiters():
    """A link failure mid-request fails a blocked call_sync promptly
    with the same typed error the async path raises — not a timeout."""
    server = TCPChannel(app="srv", codec="json")
    entered = threading.Event()

    def wedge(body, headers):
        entered.set()
        time.sleep(30)
        return {}

    server.register("t", "/wedge", wedge)
    addr = server.listen_sync("127.0.0.1", 0)
    client = TCPChannel(app="cli", codec="json")
    killed = []

    def killer():
        entered.wait(10)
        killed.append(time.perf_counter())
        server.close_sync()  # hard-fails every link

    t = threading.Thread(target=killer)
    t.start()
    try:
        t0 = time.perf_counter()
        with pytest.raises(PeerUnreachableError):
            client.call_sync(addr, "t", "/wedge", {}, timeout=25)
        # promptly after the kill — the sticky error propagated, the
        # waiter did not ride its 25 s timeout
        assert time.perf_counter() - killed[0] < 5.0
        assert time.perf_counter() - t0 < 20.0
    finally:
        t.join(timeout=10)
        client.close_sync()
        server.close_sync()


# -- coalescing ---------------------------------------------------------------


def test_coalescing_preserves_enqueue_order():
    """Frames on one link arrive in enqueue order across flush
    boundaries, and bursts actually coalesce (coalesced_frames > 0)."""
    got = []
    got_lock = threading.Lock()
    done = threading.Event()
    N = 40

    def handler(link, rid, payload):
        with got_lock:
            got.append(int(bytes(payload).decode()))
            if len(got) >= N:
                done.set()

    ledger = TransportLedger()
    server = RpcEndpoint(handler)
    client = RpcEndpoint(ledger=ledger, ledger_class="rpc", flush_us=2000.0)
    try:
        addr = server.listen("127.0.0.1", 0)
        link = client.connect(addr)
        for i in range(N):
            rid = link.alloc_id()
            link.request(rid, str(i).encode(), lambda p, lane: None)
        link.flush()
        assert done.wait(10), f"only {len(got)}/{N} frames arrived"
        with got_lock:
            assert got == list(range(N)), got
        # The sender thread accounts a batch only after sendmsg returns, so
        # the receiver can observe frames before the ledger row exists.
        deadline = time.time() + 5
        while time.time() < deadline:
            st = ledger.stats()
            if st["classes"].get("rpc", {}).get("coalesced_frames", 0) > 0:
                break
            time.sleep(0.01)
        assert st["classes"]["rpc"]["coalesced_frames"] > 0
    finally:
        client.close()
        server.close()


def test_urgent_cuts_the_flush_window():
    """With a large flush window, an urgent probe completes fast while
    a non-urgent frame waits out the window — the escape hatch works."""
    server, client, addr = _sync_pair(codec="json")
    held_client = TCPChannel(app="cli2", codec="json", flush_us=60_000.0)
    try:
        # warm both links (connection setup out of the timing)
        client.call_sync(addr, "t", "/echo", {}, timeout=10)
        held_client.call_sync(addr, "t", "/echo", {}, urgent=True, timeout=10)

        t0 = time.perf_counter()
        held_client.call_sync(addr, "t", "/echo", {"u": 1}, urgent=True,
                              timeout=10)
        urgent_rtt = time.perf_counter() - t0

        t0 = time.perf_counter()
        held_client.call_sync(addr, "t", "/echo", {"u": 0}, timeout=10)
        held_rtt = time.perf_counter() - t0

        # the held frame waits ~60 ms for company; the urgent one must
        # not (generous bounds for noisy shared containers)
        assert held_rtt > 0.03, held_rtt
        assert urgent_rtt < held_rtt / 2, (urgent_rtt, held_rtt)
    finally:
        held_client.close_sync()
        client.close_sync()
        server.close_sync()


# -- shm lane -----------------------------------------------------------------


def _wait_for_shm_traffic(ledger, deadline_s=5.0):
    deadline = time.time() + deadline_s
    while time.time() < deadline:
        lanes = ledger.stats()["classes"].get("rpc", {}).get("lanes", {})
        if lanes.get("shm", {}).get("frames_sent", 0) > 0:
            return True
        time.sleep(0.02)
    return False


def test_shm_lane_bit_identity_and_fallback():
    """Same-host pair with the shm lane on: small bodies migrate to the
    shm ring (frames counted under lane 'shm'), oversized bodies fall
    back to TCP, and every echo is bit-identical to the TCP-only run."""
    bodies = [
        {"k": i, "blob": "x" * (1 << i)} for i in range(8)
    ] + [{"big": "y" * 200_000}]  # > slot_bytes: must ride TCP

    def collect(**kw):
        server, client, addr = _sync_pair(codec="msgpack", **kw)
        try:
            if kw.get("shm_lane"):
                # negotiation is async on the link: keep echoing until a
                # frame actually rides the ring (the offer/ack handshake
                # lands within a call or two on loopback)
                deadline = time.time() + 10
                while not _wait_for_shm_traffic(client.ledger, 0.05):
                    assert time.time() < deadline, "shm lane never engaged"
                    client.call_sync(addr, "t", "/echo", {"warm": 1},
                                     timeout=10)
            out = []
            for b in bodies:
                out.append(client.call_sync(addr, "t", "/echo", b, timeout=10))
            return out, client.ledger.stats()
        finally:
            client.close_sync()
            server.close_sync()

    tcp_out, _ = collect()
    shm_out, shm_stats = collect(shm_lane=True)
    assert shm_out == tcp_out  # bit-identity across the lane combination
    lanes = shm_stats["classes"]["rpc"]["lanes"]
    assert lanes.get("shm", {}).get("frames_sent", 0) > 0
    # the oversized body rode TCP: tcp lane saw bulk bytes
    assert lanes.get("tcp", {}).get("bytes_sent", 0) > 200_000
    assert shm_stats["copy_bytes"] == 0


def test_shm_lane_with_coalescing_and_spin_off():
    """Every remaining lane combination answers identically: shm +
    coalescing, and spin_us=0 (pure blocking readers)."""
    body = {"q": list(range(50))}

    def one(**kw):
        server, client, addr = _sync_pair(codec="msgpack", **kw)
        try:
            return [
                client.call_sync(addr, "t", "/echo", body, timeout=10)
                for _ in range(10)
            ]
        finally:
            client.close_sync()
            server.close_sync()

    base = one()
    assert one(shm_lane=True, flush_us=200.0) == base
    assert one(spin_us=0.0) == base
    assert one(flush_us=200.0) == base


# -- ledger lanes -------------------------------------------------------------


def test_ledger_lane_sums_reconcile_with_class_totals():
    led = TransportLedger()
    led.add("rpc", lane="tcp", bytes_sent=100, frames_sent=2)
    led.add("rpc", lane="shm", bytes_sent=40, frames_sent=1,
            inline_completions=3)
    led.add("rpc", lane="tcp", coalesced_frames=2)
    led.add("shm", lane="shm", bytes_recv=8, frames_recv=1)
    st = led.stats()
    for klass, row in st["classes"].items():
        for f in TransportLedger.FIELDS:
            assert row[f] == sum(r[f] for r in row["lanes"].values()), (
                klass, f,
            )
    assert st["classes"]["rpc"]["bytes_sent"] == 140
    assert st["classes"]["rpc"]["inline_completions"] == 3
    assert st["classes"]["rpc"]["coalesced_frames"] == 2
    assert st["total"]["bytes_sent"] == 140
    assert st["total"]["inline_completions"] == 3
    assert st["copy_bytes"] == 0


# -- bench probe --------------------------------------------------------------


def test_trimmed_batch_median_drops_displaced_batches():
    # mostly-flat samples with one whole displaced batch (a noisy-
    # neighbor burst): the trimmed median-of-batches ignores it
    samples = [1.0] * 175 + [50.0] * 25  # the last batch of 8 displaced
    assert bench._trimmed_batch_median(samples, batches=8) == 1.0
    # degenerate sizes stay defined
    assert bench._trimmed_batch_median([3.0]) == 3.0
    with pytest.raises(ValueError):
        bench._trimmed_batch_median([])


def test_fast_and_full_mode_probes_agree():
    """The fast-mode undersampling fix: a 200-sample draw and a
    1000-sample draw from the same jittery latency distribution produce
    trimmed batch-medians that agree within noise (the raw p50s of the
    same draws historically disagreed by far more)."""
    import random

    rng = random.Random(7)

    def draw(n):
        out = []
        for i in range(n):
            x = rng.gauss(80.0, 6.0)
            if rng.random() < 0.06:
                x += rng.uniform(200.0, 1500.0)  # scheduler spikes
            out.append(max(x, 40.0))
        return out

    full = bench._trimmed_batch_median(draw(1000))
    fast = bench._trimmed_batch_median(draw(200))
    assert abs(fast - full) / full < 0.05, (fast, full)


def test_transport_rtt_probe_shape():
    """The live probe emits both percentiles and stays sane (an in-
    process loopback RTT is microseconds, not milliseconds-scale)."""
    r = bench._transport_rtt_us(60, codec="msgpack")
    assert set(r) == {"p50_us", "p99_us"}
    assert 0 < r["p50_us"] <= r["p99_us"]
    assert r["p50_us"] < 50_000  # no pathological stall


def test_sync_server_garbage_frame_still_drops_connection():
    """The r23 reader-thread dispatch path keeps the pre-r21 garbage
    contract: an undecodable REQUEST body kills only its own link."""
    server, client, addr = _sync_pair(codec="json")
    try:
        import socket as socketlib

        host, port = addr.rsplit(":", 1)
        raw = socketlib.create_connection((host, int(port)), timeout=5)
        try:
            raw.sendall(_HDR.pack(TAG_RPC_REQ | 7, 1, 4) + b"\xff\xfe\xfd\xfc")
            raw.settimeout(5)
            assert raw.recv(64) == b""  # server dropped the connection
        finally:
            raw.close()
        # other links unaffected
        assert client.call_sync(addr, "t", "/echo", {"a": 1}, timeout=10) == {
            "a": 1
        }
    finally:
        client.close_sync()
        server.close_sync()
