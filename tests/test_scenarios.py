"""Batched chaos fleet (r12): stacking semantics, B=1 bit-identity, and
the scenario-grid compiler.

The tentpole claim is strong: a stacked ``[B, ...]`` FaultPlan run
through the vmapped fleet is bit-for-bit the B solo runs — state AND
telemetry — with materialized default legs changing nothing.  These
tests pin that, plus the grid compiler's parity contract with the
committed mc_churn 1-D slice (same rng sequence → same masks → the
loss-0 surface row IS the slice).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ringpop_tpu.sim import chaos, delta, lifecycle, scenarios, telemetry
from ringpop_tpu.sim.chaos import FaultPlan
from ringpop_tpu.sim.montecarlo import MonteCarlo

N, K = 128, 16
PARAMS = dict(n=N, k=K, suspect_ticks=6, rng="counter")


# -- stacking semantics -------------------------------------------------------


def test_stack_plans_legs_and_defaults():
    plans = [
        chaos.scenario_plan("churn", N, seed=0, horizon=64),
        chaos.scenario_plan("asym", N, seed=1, horizon=64),
    ]
    stacked = chaos.stack_plans(plans)
    assert chaos.plan_batch_size(stacked) == 2
    # churn member materialized an identity reach (the asym member has one)
    assert stacked.reach.shape == (2, 2, 2)
    np.testing.assert_array_equal(np.asarray(stacked.reach[0]), np.eye(2, dtype=bool))
    # legs set by NO member stay None (compile out)
    assert stacked.drop_node is None
    # crash legs: member 1 (asym rides a small churn cohort) keeps its own
    np.testing.assert_array_equal(
        np.asarray(stacked.crash_tick[0]), np.asarray(plans[0].crash_tick)
    )


def test_stack_plans_rejects_already_stacked_and_empty():
    stacked = chaos.stack_plans([chaos.scenario_plan("churn", N, seed=0)])
    with pytest.raises(ValueError, match="SOLO"):
        chaos.stack_plans([stacked])
    with pytest.raises(ValueError, match="at least one"):
        chaos.stack_plans([])


def test_plan_axes_and_index_round_trip():
    plans = [
        chaos.scenario_plan("churn", N, seed=0, horizon=64),
        chaos.scenario_plan("flap", N, seed=1, horizon=64),
    ]
    stacked = chaos.stack_plans(plans)
    axes = chaos.plan_axes(stacked)
    for field in stacked._fields:
        leg, ax = getattr(stacked, field), getattr(axes, field)
        assert (leg is None) == (ax is None), field
        if leg is not None:
            assert ax == 0
    # solo plans report nothing batched
    assert chaos.plan_axes(plans[0]) is None
    assert chaos.plan_batch_size(plans[0]) is None
    # index_plan(stack_plans(ps), b) evaluates like ps[b] at every tick
    for b in range(2):
        member = chaos.index_plan(stacked, b)
        for t in (0, 7, 31, 63):
            got = chaos.up_at_host(member, t, N)
            want = chaos.up_at_host(plans[b], t, N)
            np.testing.assert_array_equal(got, want, err_msg=f"b={b} t={t}")


def test_mixed_batch_sizes_rejected():
    a = FaultPlan(drop_rate=jnp.zeros((2,), jnp.float32))
    b = FaultPlan(base_up=jnp.ones((3, N), bool))
    merged = FaultPlan(drop_rate=a.drop_rate, base_up=b.base_up)
    with pytest.raises(ValueError, match="mixed batch sizes"):
        chaos.plan_batch_size(merged)


def test_default_legs_are_value_neutral():
    """A plan stacked alongside a leg-richer member must produce the SAME
    trajectory it produces solo: the materialized defaults (NO_TICK crash
    windows, zero flap periods, group -1, 0.0 loss, identity reach) are
    inert by construction."""
    lean = chaos.churn_plan(N, n_churn=4, n_permanent=2, first=4, waves=2, seed=3)
    rich = chaos.scenario_plan("asym", N, seed=1, horizon=64)
    stacked = chaos.stack_plans([lean, rich])
    params = lifecycle.LifecycleParams(**PARAMS)
    mc = MonteCarlo(params, [5, 6])
    mc.run(24, stacked)
    solo = lifecycle.LifecycleSim(seed=5, **PARAMS)
    solo.run(24, lean)
    for field in solo.state._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(mc.states, field))[0],
            np.asarray(getattr(solo.state, field)),
            err_msg=field,
        )


def test_run_until_detected_carries_armed_telemetry():
    """r19 (was a refusal in r12): the fleet detection loop CARRIES an
    armed accumulator in its while carry, so long-horizon sweeps journal
    counters without falling back to fixed-horizon stepping.  The
    fetched block must cover exactly the ticks the lockstep fleet
    stepped, match a solo telemetry run of the same length field for
    field, and the state must equal the telemetry-off run's (counters
    never perturb the trajectory)."""
    params = lifecycle.LifecycleParams(**PARAMS)
    victims = [3]
    up = np.ones(N, bool)
    up[victims] = False
    faults = delta.DeltaFaults(up=jnp.asarray(up))
    mc = MonteCarlo(params, [0], telemetry=True)
    ticks, det = mc.run_until_detected(
        victims, faults, max_ticks=256, check_every=8
    )
    assert bool(det[0])
    rec = mc.fetch_telemetry(faults)[0]

    mc_off = MonteCarlo(params, [0])
    ticks_off, det_off = mc_off.run_until_detected(
        victims, faults, max_ticks=256, check_every=8
    )
    assert int(ticks[0]) == int(ticks_off[0]) and bool(det[0]) == bool(det_off[0])
    assert rec["state_digest"] == int(
        telemetry.tree_digest(jax.tree.map(lambda x: x[0], mc_off.states))
    )

    # the counters cover every tick the lockstep fleet actually stepped
    # (first-detection ticks are a lower bound; here B=1 so they agree)
    total = int(rec["ticks"])
    assert total == int(ticks[0])
    sink = telemetry.TelemetrySink()
    sim = lifecycle.LifecycleSim(seed=0, telemetry=sink, **PARAMS)
    sim.run(total, faults)
    solo = sink.records[0]
    for key in ("ping_send", "ping_req_send", "refuted", "decl_suspect",
                "decl_faulty", "timer_fired", "ticks"):
        assert rec[key] == solo[key], key


# -- B=1 / heterogeneous bit-identity (the ISSUE 7 acceptance pins) ----------


def test_b1_stacked_lifecycle_bit_identical_state_and_telemetry():
    plan = chaos.scenario_plan("smoke", N, seed=0, horizon=64)
    params = lifecycle.LifecycleParams(**PARAMS)
    mc = MonteCarlo(params, [0], telemetry=True)
    fleet_blocks = []
    for _ in range(4):
        mc.run(16, chaos.stack_plans([plan]))
        fleet_blocks.append(mc.fetch_telemetry(chaos.stack_plans([plan]))[0])

    sink = telemetry.TelemetrySink()
    sim = lifecycle.LifecycleSim(seed=0, telemetry=sink, **PARAMS)
    for _ in range(4):
        sim.run(16, plan)

    assert fleet_blocks[-1]["state_digest"] == int(telemetry.tree_digest(sim.state))
    for field in sim.state._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(mc.states, field))[0],
            np.asarray(getattr(sim.state, field)),
            err_msg=field,
        )
    for i, (got, want) in enumerate(zip(fleet_blocks, sink.records)):
        for key, v in want.items():
            if key == "state_digest":
                continue
            assert got[key] == v, (i, key, got[key], v)


def test_b1_stacked_delta_bit_identical():
    """The delta engine batches through the same seam: a B=1 stacked plan
    vmapped over ``delta.step`` ends bit-identical (state digest AND
    coverage record) to the solo DeltaSim chaos run."""
    plan = chaos.scenario_plan("smoke", N, seed=0, horizon=64)
    stacked = chaos.stack_plans([plan])
    axes = chaos.plan_axes(stacked)
    params = delta.DeltaParams(n=N, k=K, rng="counter")
    state_b = jax.tree.map(lambda x: x[None], delta.init_state(params, seed=0))
    vstep = jax.vmap(lambda s, p: delta.step(params, s, p), in_axes=(0, axes))
    blk = jax.jit(lambda s, p: jax.lax.fori_loop(0, 32, lambda _, c: vstep(c, p), s))
    out = blk(state_b, stacked)

    sim = delta.DeltaSim(n=N, k=K, seed=0, rng="counter")
    for _ in range(32):
        sim.tick(plan)
    for field in sim.state._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(out, field))[0],
            np.asarray(getattr(sim.state, field)),
            err_msg=field,
        )
    assert int(telemetry.tree_digest(jax.tree.map(lambda x: x[0], out))) == int(
        telemetry.tree_digest(sim.state)
    )
    rec_fleet = telemetry.delta_record(jax.tree.map(lambda x: x[0], out), plan)
    rec_solo = telemetry.delta_record(sim.state, plan)
    assert float(rec_fleet["coverage"]) == float(rec_solo["coverage"])


def test_heterogeneous_batch_reproduces_solo_digests():
    plans = [
        chaos.scenario_plan("churn", N, seed=0, horizon=64),
        chaos.scenario_plan("flap", N, seed=1, horizon=64),
        chaos.scenario_plan("asym", N, seed=2, horizon=64),
    ]
    stacked = chaos.stack_plans(plans)
    seeds = [3, 7, 11]
    params = lifecycle.LifecycleParams(**PARAMS)
    mc = MonteCarlo(params, seeds, telemetry=True)
    mc.run(32, stacked)
    recs = mc.fetch_telemetry(stacked)
    assert [r["scenario_id"] for r in recs] == [0, 1, 2]
    for b, (plan, seed) in enumerate(zip(plans, seeds)):
        sink = telemetry.TelemetrySink()
        sim = lifecycle.LifecycleSim(seed=seed, telemetry=sink, **PARAMS)
        sim.run(32, plan)
        assert recs[b]["state_digest"] == int(telemetry.tree_digest(sim.state)), b
        for key in ("ping_send", "refuted", "decl_suspect", "detect_frac",
                    "census_alive", "heal_attempts"):
            assert recs[b][key] == sink.records[0][key], (b, key)


# -- the scenario-grid compiler ----------------------------------------------


def test_grid_meta_ordering_and_seeds():
    doses = [0, 4, 8]
    plan, meta = scenarios.scenario_grid(
        N, victims=[3, 9], doses=doses, losses=(0.0, 0.1), churn_seed=1
    )
    assert chaos.plan_batch_size(plan) == 6
    assert [m["churn"] for m in meta] == doses * 2
    assert [m["loss"] for m in meta] == [0.0] * 3 + [0.1] * 3
    assert scenarios.grid_seeds(meta, 100) == [100, 101, 102, 100, 101, 102]
    # dose masks shared across loss rows (drawn once per dose)
    np.testing.assert_array_equal(
        np.asarray(plan.base_up[1]), np.asarray(plan.base_up[4])
    )


def test_churn_masks_match_mc_churn_rng_sequence():
    """The parity contract under the loss-0 surface row: same rng
    consumption as detection_latency_under_churn's mask loop."""
    victims = [3, 9]
    doses = scenarios.mc_churn_doses(4, 12)
    masks = scenarios.churn_dose_masks(N, victims, doses, churn_seed=77)
    rng = np.random.default_rng(77)
    candidates = np.setdiff1d(np.arange(N), np.asarray(victims, np.int64))
    up = np.ones((4, N), bool)
    up[:, victims] = False
    for b in range(4):
        extra = round(b / 3 * 12)
        if extra:
            up[b, rng.choice(candidates, size=extra, replace=False)] = False
    np.testing.assert_array_equal(masks, up)


def test_loss0_row_matches_unbatched_churn_study():
    """End-to-end parity at test scale: the fleet's loss-0 row equals the
    committed 1-D study machinery tick-for-tick (same seeds, same masks,
    same detection predicate at 1-tick resolution)."""
    from ringpop_tpu.sim.montecarlo import detection_latency_under_churn

    n, b, seed = 256, 4, 0
    rng = np.random.default_rng(seed)
    victims = sorted(rng.choice(n, size=2, replace=False).tolist())
    out = detection_latency_under_churn(
        n=n, seeds=range(seed, seed + b), victims=victims, churn_max=8,
        k=16, max_ticks=512, churn_seed=seed + 777,
    )
    doses = scenarios.mc_churn_doses(b, 8)
    plan, meta = scenarios.scenario_grid(
        n, victims=victims, doses=doses, losses=(0.0, 0.05),
        churn_seed=seed + 777,
    )
    params = lifecycle.LifecycleParams(n=n, k=16)
    ticks, det, _ = scenarios.detect_surface(
        params, plan, scenarios.grid_seeds(meta, seed), victims,
        max_ticks=512, check_every=1,
    )
    row0 = [int(t) if d else None for t, d in zip(ticks[:b], det[:b])]
    assert row0 == [t for _, t in out["churn_ticks"]]


def test_response_surface_and_cliff():
    meta = [
        {"churn": c, "loss": l} for l in (0.0, 0.1) for c in (0, 10, 20)
    ]
    values = [10, 11, 40, 12, None, 44]
    surf = scenarios.response_surface(meta, values, rows="loss", cols="churn")
    assert surf["rows"] == [0.0, 0.1] and surf["cols"] == [0, 10, 20]
    assert surf["cells"] == [[10.0, 11.0, 40.0], [12.0, None, 44.0]]
    at, jump = scenarios.locate_cliff(list(zip(surf["cols"], surf["cells"][0])))
    assert (at, jump) == (20, 29.0)
    assert scenarios.locate_cliff([(0, None), (1, 5)]) == (None, None)


def test_locate_cliff_contract():
    """The explicit empty/short-input contract (r19): (None, None) ONLY
    for curves too short to define a jump; (None, 0.0) for well-defined
    curves with no positive jump; ties break to the larger dose."""
    # too short: empty, single point, all-undetected
    assert scenarios.locate_cliff([]) == (None, None)
    assert scenarios.locate_cliff([(5, 12)]) == (None, None)
    assert scenarios.locate_cliff([(0, None), (1, None)]) == (None, None)
    assert scenarios.locate_cliff([(0, None), (1, 5)]) == (None, None)
    # monotone-flat / non-increasing: a curve with NO cliff, jump 0.0
    assert scenarios.locate_cliff([(0, 10), (1, 10), (2, 10)]) == (None, 0.0)
    assert scenarios.locate_cliff([(0, 30), (1, 20), (2, 10)]) == (None, 0.0)
    # the 2-cell windows the adaptive driver hands it
    assert scenarios.locate_cliff([(4, 10), (5, 40)]) == (5, 30)
    assert scenarios.locate_cliff([(4, 10), (5, 10)]) == (None, 0.0)
    # tie on jump -> larger dose
    assert scenarios.locate_cliff([(0, 0), (1, 10), (2, 20)]) == (2, 10)


def test_refine_surface_matches_dense_with_fewer_evals():
    """The adaptive driver on a surface with a dominant cliff: identical
    cliff coordinate to the dense 1-dose grid, strictly fewer
    scenario-evaluations, ONE compiled program for every dispatch."""
    n = 512
    params = lifecycle.LifecycleParams(n=n, k=16)
    rng = np.random.default_rng(0)
    victims = sorted(rng.choice(n, size=4, replace=False).tolist())
    kw = dict(
        victims=victims, losses=(0.0,), max_dose=64, churn_seed=777,
        max_ticks=1024, check_every=1,
    )
    ad = scenarios.refine_surface(params, coarse=9, **kw)
    de = scenarios.dense_surface(params, **kw)
    assert de.get("all_detected") and ad.get("all_detected")
    assert ad["cliffs"][0.0]["cliff_at"] == de["cliffs"][0.0]["cliff_at"]
    assert ad["cliffs"][0.0]["jump"] == de["cliffs"][0.0]["jump"]
    assert ad["evals_unique"] < de["evals_unique"] / 2
    # O(log) outer loop: coarse + bisect rounds + verify, not O(doses)
    assert ad["dispatches"] <= 3 + int(np.ceil(np.log2(64)))


def test_refine_runner_compiles_once():
    """Value-only plan swaps: with the AOT front door on, every
    dispatch of the adaptive driver's runner reuses the ONE keyed
    program — different doses, losses and seeds are value swaps, never
    new signatures (the memo gains the fleet sharding descriptor, so
    this is also the key-stability pin)."""
    n = 256
    params = lifecycle.LifecycleParams(n=n, k=16)
    masks = scenarios.dose_mask_table(n, [3, 9], 16, churn_seed=7)
    runner = scenarios._CliffRunner(
        params, [3, 9], masks, width=4, base_seed=0, max_ticks=512,
        check_every=4, aot="refine-test",
    )
    runner.eval([(0, 0.0), (4, 0.0), (8, 0.0), (12, 0.0)])
    runner.eval([(2, 0.05), (6, 0.1)])  # new doses AND new loss values
    assert runner.dispatches == 2
    assert len(runner.mc._aot_calls) == 1
    assert runner.result_fields()["compiled_programs"] == 1


def test_scored_fleet_verdicts_carry_grid_coordinates():
    plan, meta = scenarios.scenario_grid(
        N, victims=[3, 9], doses=[0, 4], losses=(0.0, 0.1), churn_seed=1
    )
    params = lifecycle.LifecycleParams(**PARAMS)
    scores = scenarios.scored_fleet(
        params, plan, meta, scenarios.grid_seeds(meta, 0), horizon=32,
        journal_every=16, scenario="test",
    )
    assert len(scores) == 4
    for i, s in enumerate(scores):
        assert s["scenario_id"] == i
        assert s["kind"] == "score" and s["scenario"] == "test"
        assert (s["churn"], s["loss"]) == (meta[i]["churn"], meta[i]["loss"])
        assert s["blocks"] == 2 and s["ticks"] == 32


def test_split_batched_one_fetch_per_block():
    rec = {"a": jnp.arange(3), "b": jnp.float32(1.5), "tick": jnp.asarray([4, 4, 4])}
    out = telemetry.split_batched(rec, {"extra": jnp.asarray([7, 8, 9])})
    assert [r["scenario_id"] for r in out] == [0, 1, 2]
    assert [r["a"] for r in out] == [0, 1, 2]
    assert all(r["b"] == 1.5 for r in out)
    assert [r["extra"] for r in out] == [7, 8, 9]


def test_sweep_static_suspect_ticks_outer_axis():
    """The fourth grid axis: suspicion timeout cannot ride the batch
    dimension (a compile-time constant is a different program), so it
    sweeps as a static outer loop — ``sweep_static`` composing with the
    batched fleet, one compiled program per timeout value.  Longer
    suspicion must never speed up faulty declaration."""
    plan, meta = scenarios.scenario_grid(
        N, victims=[3, 9], doses=[0, 4], losses=(0.0,), churn_seed=1
    )
    seeds = scenarios.grid_seeds(meta, 0)

    def run(suspect_ticks):
        params = lifecycle.LifecycleParams(
            n=N, k=K, suspect_ticks=suspect_ticks, rng="counter"
        )
        ticks, detected, _ = scenarios.detect_surface(
            params, plan, seeds, [3, 9], max_ticks=256
        )
        assert bool(np.asarray(detected).all())
        return [int(t) for t in ticks]

    out = scenarios.sweep_static([4, 12], run)
    assert sorted(out) == [4, 12]
    assert all(a <= b for a, b in zip(out[4], out[12]))
    assert out[4] != out[12]  # the timeout genuinely moved detection


def test_plan_events_stacked_defaults_are_eventless():
    """The materialized stacked defaults must be event-neutral too: a
    part=0 member of a partitioned grid reports NO partition/heal events,
    and a never-healing split (part_until=None -> NO_TICK in the stacked
    encoding) reports a partition but NO heal — same as its solo form."""
    plan, meta = scenarios.scenario_grid(
        N, victims=[3], doses=[0], losses=(0.0,), parts=(0.0, 0.25),
        churn_seed=1, part_from=2, part_until=None,
    )
    kinds0 = [e["kind"] for e in chaos.plan_events(chaos.index_plan(plan, 0))]
    assert "partition" not in kinds0 and "heal" not in kinds0
    events1 = chaos.plan_events(chaos.index_plan(plan, 1))
    kinds1 = [e["kind"] for e in events1]
    assert "partition" in kinds1 and "heal" not in kinds1
    part = next(e for e in events1 if e["kind"] == "partition")
    assert part["tick"] == 2 and part["nodes"] == N // 4


def test_stack_plans_reach_pads_to_symmetric_group_range():
    """The padded identity reach must cover every member's group-id
    range, not just the reach-carrying members' G: a symmetric member
    using group id 2 stacked with a [2,2]-reach member previously got
    eye(2), and its id-2 rows clamped into group 1's — connecting groups
    its solo run keeps apart."""
    group = np.full(N, -1, np.int32)
    group[:4], group[4:8], group[8:12] = 0, 1, 2
    sym = FaultPlan(
        group=jnp.asarray(group),
        part_from=jnp.asarray(0, jnp.int32),
        part_until=jnp.asarray(64, jnp.int32),
    )
    asym = chaos.scenario_plan("asym", N, seed=1, horizon=64)  # reach [2, 2]
    stacked = chaos.stack_plans([sym, asym])
    assert stacked.reach.shape[1:] == (3, 3)
    np.testing.assert_array_equal(np.asarray(stacked.reach[0]), np.eye(3, dtype=bool))
    a = jnp.asarray([0, 4, 8, 8], jnp.int32)  # groups 0, 1, 2, 2
    b = jnp.asarray([4, 8, 9, 0], jnp.int32)  # groups 1, 2, 2, 0
    solo = delta.pair_connected(chaos.faults_at(sym, jnp.int32(1)), a, b)
    member = delta.pair_connected(
        chaos.faults_at(chaos.index_plan(stacked, 0), jnp.int32(1)), a, b
    )
    assert np.asarray(solo).tolist() == [False, False, True, False]
    np.testing.assert_array_equal(np.asarray(member), np.asarray(solo))
