"""Serve-the-ring tier: device ring state, micro-batching collector,
shared-memory + TCP transports, DGRO placement (ringpop_tpu/serve/)."""

import asyncio
import threading
import time

import numpy as np
import pytest

import jax.numpy as jnp

from ringpop_tpu.hashring import HashRing
from ringpop_tpu.serve.bench import ServiceThread
from ringpop_tpu.serve.client import HostBisectFrontend, ServeClient
from ringpop_tpu.serve.service import RingService
from ringpop_tpu.serve.state import (
    RingStore,
    serve_lookup,
    serve_lookup_fused,
    serve_lookup_n,
    serve_lookup_n_fused,
)

SERVERS = [f"10.7.0.{i}:3000" for i in range(24)]


def _hashes(n, seed=0):
    return np.random.default_rng(seed).integers(0, 2**32, size=n, dtype=np.uint32)


class _Journal:
    def __init__(self):
        self.records = []

    def _write(self, obj):
        self.records.append(obj)


class _Stats:
    def __init__(self):
        self.counts = {}
        self.gauges = {}
        self.timings = []

    def incr(self, key, n=1):
        self.counts[key] = self.counts.get(key, 0) + n

    def gauge(self, key, v):
        self.gauges[key] = v

    def timing(self, key, v):
        self.timings.append((key, v))


# -- RingStore / DeviceRing --------------------------------------------------


def test_store_lookup_matches_host_oracle():
    store = RingStore(SERVERS, replica_points=20)
    ring, gen, _ = store.snapshot()
    probe = _hashes(512)
    dev = np.asarray(serve_lookup(ring, jnp.asarray(probe))[0])
    oracle = HostBisectFrontend(SERVERS, 20).lookup_hashes(probe)
    assert np.array_equal(dev, oracle)
    assert gen == 0


def test_store_update_bumps_generation_and_swaps_values():
    store = RingStore(SERVERS, replica_points=10)
    probe = _hashes(256, seed=1)
    rec = store.update(add=["10.7.1.1:3000"], remove=[SERVERS[0]])
    assert rec["gen"] == 1 and rec["kind"] == "ring_update"
    assert rec["added"] == ["10.7.1.1:3000"] and rec["removed"] == [SERVERS[0]]
    ring, gen, _ = store.snapshot()
    owners, dev_gen = serve_lookup(ring, jnp.asarray(probe))
    assert int(np.asarray(dev_gen)[0]) == gen == 1
    live = store.servers_at(1)
    oracle = HostBisectFrontend(live, 10).lookup_hashes(probe)
    assert np.array_equal(np.asarray(owners), oracle)
    # no-op update commits nothing
    assert store.update(add=["10.7.1.1:3000"]) is None
    assert store.gen == 1


def test_store_checksum_tracks_host_ring():
    store = RingStore(SERVERS[:4], replica_points=10)
    rec = store.update(add=["b:1"])
    oracle = HashRing(replica_points=10)
    oracle.add_remove_servers(sorted(SERVERS[:4]) + ["b:1"], [])
    assert rec["checksum"] == oracle.checksum()


def test_store_capacity_reallocates_on_overflow():
    store = RingStore(SERVERS[:2], replica_points=10, capacity=25)
    assert store.capacity == 25
    rec = store.update(add=["c:1"])  # 30 tokens > 25
    assert rec["reallocated"] and rec["count"] == 30
    assert store.capacity >= 30
    probe = _hashes(64, seed=2)
    ring, gen, _ = store.snapshot()
    dev = np.asarray(serve_lookup(ring, jnp.asarray(probe))[0])
    oracle = HostBisectFrontend(store.servers_at(gen), 10).lookup_hashes(probe)
    assert np.array_equal(dev, oracle)


def test_store_generation_ring_buffer_ages_out():
    store = RingStore(SERVERS[:3], replica_points=5, keep_generations=2)
    for i in range(4):
        store.update(add=[f"x{i}:1"])
    assert store.servers_at(store.gen) is not None
    assert store.servers_at(store.gen - 1) is not None
    assert store.servers_at(0) is None


def test_store_host_mirror_matches_device():
    store = RingStore(SERVERS, replica_points=10)
    store.update(add=["z:9"])
    toks, owns, gen, _ns = store.snapshot_host()
    probe = _hashes(256, seed=3)
    idx = np.searchsorted(toks, probe, side="left")
    host = owns[np.where(idx == toks.shape[0], 0, idx)]
    ring, dgen, _ = store.snapshot()
    dev = np.asarray(serve_lookup(ring, jnp.asarray(probe))[0])
    assert gen == dgen and np.array_equal(host, dev)


def test_store_listens_to_live_ring_changes():
    """The live-update feed: RingChangedEvents from a host HashRing drive
    committed generations."""
    store = RingStore(SERVERS[:4], replica_points=10)
    live = HashRing(replica_points=10)
    live.add_remove_servers(SERVERS[:4], [])
    store.listen_to(live)
    live.add_remove_servers(["new:1"], [SERVERS[0]])
    assert store.gen == 1
    assert "new:1" in store.servers_at(1)
    assert SERVERS[0] not in store.servers_at(1)


def test_serve_lookup_fused_matches_pair():
    store = RingStore(SERVERS[:6], replica_points=10)
    store.update(add=["q:1"])
    ring, gen, _ = store.snapshot()
    probe = _hashes(33, seed=4)
    owners, dev_gen = serve_lookup(ring, jnp.asarray(probe))
    fused = np.asarray(serve_lookup_fused(ring, jnp.asarray(probe)))
    assert np.array_equal(fused[:-1], np.asarray(owners))
    assert fused[-1] == int(np.asarray(dev_gen)[0]) == gen


def test_serve_lookup_n_preference_lists():
    store = RingStore(SERVERS[:8], replica_points=10)
    ring, gen, ns = store.snapshot()
    probe = _hashes(64, seed=5)
    out, _ = serve_lookup_n(ring, ns, jnp.asarray(probe), 3)
    out = np.asarray(out)
    host = HashRing(replica_points=10)
    host.add_remove_servers(SERVERS[:8], [])
    slist = host.servers()
    for i, h in enumerate(probe.tolist()):
        want = [slist.index(s) for s in host._lookup_n_hash(h, 3)]
        assert list(out[i]) == want


def test_empty_store_answers_minus_one():
    store = RingStore([], replica_points=10)
    ring, gen, _ = store.snapshot()
    out = np.asarray(serve_lookup(ring, jnp.asarray(_hashes(8)))[0])
    assert (out == -1).all() and gen == 0


# -- the micro-batching collector -------------------------------------------


def test_collector_coalesces_same_iteration_submits():
    journal = _Journal()
    stats = _Stats()
    store = RingStore(SERVERS, replica_points=10)
    svc = RingService(store, flush_us=0.0, journal=journal, stats=stats,
                      journal_every=1)
    h1, h2, h3 = _hashes(40, 1), _hashes(50, 2), _hashes(60, 3)

    async def main():
        f1 = svc.submit(h1)
        f2 = svc.submit(h2)
        f3 = svc.submit(h3)
        return await asyncio.gather(f1, f2, f3)

    results = asyncio.run(main())
    oracle = HostBisectFrontend(SERVERS, 10)
    for h, (owners, gen) in zip((h1, h2, h3), results):
        assert np.array_equal(owners, oracle.lookup_hashes(h))
        assert gen == 0
    # ONE flush carried all three requests
    assert svc.telemetry.flushes_total == 1
    assert svc.telemetry.requests_total == 3
    assert svc.telemetry.keys_total == 150
    assert stats.counts["ringpop.serve.flushes"] == 1
    rec = journal.records[-1]
    assert rec["kind"] == "serve" and rec["requests"] == 3 and rec["keys"] == 150
    assert {"mean", "p50", "p90", "max"} <= set(rec["keys_per_flush"])
    assert {"mean", "p50", "p90", "max"} <= set(rec["queue_wait_us"])


def test_collector_size_trigger_flushes_immediately():
    store = RingStore(SERVERS, replica_points=10)
    svc = RingService(store, flush_us=10_000_000.0, max_batch=64)

    async def main():
        t0 = time.perf_counter()
        f = svc.submit(_hashes(80))  # over max_batch: no waiting for the timer
        out = await f
        return out, time.perf_counter() - t0

    (owners, gen), dt = asyncio.run(main())
    assert len(owners) == 80 and dt < 5.0


def test_collector_latency_trigger_fires():
    store = RingStore(SERVERS, replica_points=10)
    svc = RingService(store, flush_us=2000.0, max_batch=1 << 20)

    async def main():
        f = svc.submit(_hashes(16))
        return await asyncio.wait_for(f, timeout=10)

    owners, gen = asyncio.run(main())
    assert len(owners) == 16


def test_collector_groups_by_n():
    store = RingStore(SERVERS[:8], replica_points=10)
    svc = RingService(store, flush_us=0.0)
    h = _hashes(32, seed=7)

    async def main():
        f1 = svc.submit(h, n=1)
        f2 = svc.submit(h, n=3)
        return await asyncio.gather(f1, f2)

    (o1, g1), (o3, g3) = asyncio.run(main())
    host = HashRing(replica_points=10)
    host.add_remove_servers(SERVERS[:8], [])
    slist = host.servers()
    for i, hh in enumerate(h.tolist()):
        want = [slist.index(s) for s in host._lookup_n_hash(hh, 3)]
        assert list(np.asarray(o3).reshape(-1, 3)[i]) == want
        assert o1[i] == want[0]
    assert svc.telemetry.flushes_total == 1  # one flush, two dispatches


def test_collector_rejects_bad_n():
    store = RingStore(SERVERS[:4], replica_points=5)
    svc = RingService(store)

    async def main():
        svc.submit(_hashes(4), n=0)

    with pytest.raises(ValueError):
        asyncio.run(main())


def test_dispatch_direct_matches_collector_and_telemeters():
    journal = _Journal()
    store = RingStore(SERVERS, replica_points=10)
    svc = RingService(store, journal=journal, journal_every=1)
    got = {}
    h = _hashes(8, seed=9)
    svc.dispatch_direct(h, 1, lambda rows, gen: got.update(rows=rows, gen=gen))
    oracle = HostBisectFrontend(SERVERS, 10).lookup_hashes(h)
    assert np.array_equal(got["rows"], oracle) and got["gen"] == 0
    assert journal.records[-1]["kind"] == "serve"
    # n>1 answers from the SAME host mirror through the exact LookupN
    # walk — the tuple must match the fused device dispatch bit-for-bit
    svc.dispatch_direct(h, 2, lambda rows, gen: got.update(rows2=rows))
    assert got["rows2"].shape == (8, 2)
    assert np.array_equal(got["rows2"][:, 0], oracle)
    ring, _g, ns = store.snapshot()
    fused = np.asarray(serve_lookup_n_fused(ring, ns, jnp.asarray(h), 2))
    assert np.array_equal(got["rows2"], fused[:-1].reshape(8, 2))


def test_ring_update_journal_and_stats():
    journal = _Journal()
    stats = _Stats()
    store = RingStore(SERVERS[:4], replica_points=10)
    svc = RingService(store, journal=journal, stats=stats)
    store.update(add=["w:1"])
    rec = journal.records[-1]
    assert rec["kind"] == "ring_update" and rec["gen"] == 1
    assert rec["n_servers"] == 5 and not rec["reallocated"]
    assert stats.gauges["ringpop.serve.ring.servers"] == 5
    assert stats.counts["ringpop.serve.ring.changed"] == 1


# -- transports ---------------------------------------------------------------


@pytest.fixture
def service_thread():
    store = RingStore(SERVERS, replica_points=10)
    th = ServiceThread(store, flush_us=0.0, shm_slots=2, shm_key_cap=4096,
                       shm_max_n=4)
    th.start()
    yield th
    th.stop()


def test_tcp_roundtrip_and_generation_fetch(service_thread):
    th = service_thread

    async def main():
        from ringpop_tpu.net import TCPChannel

        chan = TCPChannel(app="t")
        client = ServeClient(chan, th.hostport)
        h = _hashes(96, seed=11)
        owners, gen = await client.lookup_hashes(h)
        servers = await client.servers_at(gen)
        o3, g3 = await client.lookup_hashes(h[:8], n=3)
        resolved = await client.lookup(h[:4])
        await chan.close()
        return owners, gen, servers, o3, resolved

    owners, gen, servers, o3, resolved = asyncio.run(main())
    assert gen == 0 and servers == sorted(SERVERS)
    oracle = HostBisectFrontend(SERVERS, 10)
    h = _hashes(96, seed=11)
    assert np.array_equal(owners, oracle.lookup_hashes(h))
    assert o3.shape == (8, 3)
    assert resolved == [sorted(SERVERS)[o] for o in oracle.lookup_hashes(h[:4])]


def test_shm_roundtrip_small_and_large(service_thread):
    """The shared-memory transport: small batches ride the degenerate fast
    lane, large ones the collector — both must match the oracle, and n>1
    must reshape correctly."""
    from ringpop_tpu.serve.shm import ShmClient

    th = service_thread
    name, sock, slots, cap, max_n = th.shm_address()
    out = {}

    def client_run():
        cl = ShmClient(name, sock, 0, slots=slots, key_cap=cap, max_n=max_n)
        small = _hashes(8, seed=13)
        big = _hashes(600, seed=14)
        out["small"] = cl.lookup_hashes(small)
        out["big"] = cl.lookup_hashes(big)
        out["n3"] = cl.lookup_hashes(small, n=3)
        with pytest.raises(ValueError):
            cl.lookup_hashes(_hashes(cap + 1))
        with pytest.raises(ValueError):
            cl.lookup_hashes(small, n=max_n + 1)
        cl.close()

    t = threading.Thread(target=client_run)
    t.start()
    t.join(timeout=60)
    assert not t.is_alive()
    oracle = HostBisectFrontend(SERVERS, 10)
    small, big = _hashes(8, seed=13), _hashes(600, seed=14)
    o_small, g_small = out["small"]
    o_big, g_big = out["big"]
    assert np.array_equal(o_small, oracle.lookup_hashes(small))
    assert np.array_equal(o_big, oracle.lookup_hashes(big))
    assert g_small == g_big == 0
    o3, _ = out["n3"]
    assert o3.shape == (8, 3)
    assert np.array_equal(o3[:, 0], oracle.lookup_hashes(small))


def test_shm_sees_new_generation_after_update(service_thread):
    from ringpop_tpu.serve.shm import ShmClient

    th = service_thread
    th.store.update(add=["gen:1"])
    name, sock, slots, cap, max_n = th.shm_address()
    out = {}

    def client_run():
        cl = ShmClient(name, sock, 1, slots=slots, key_cap=cap, max_n=max_n)
        out["r"] = cl.lookup_hashes(_hashes(128, seed=15))
        cl.close()

    t = threading.Thread(target=client_run)
    t.start()
    t.join(timeout=60)
    owners, gen = out["r"]
    assert gen == 1
    oracle = HostBisectFrontend(
        th.store.servers_at(1), 10
    ).lookup_hashes(_hashes(128, seed=15))
    assert np.array_equal(owners, oracle)


# -- DGRO placement -----------------------------------------------------------


def test_dgro_movement_gate_and_zero_excess():
    from ringpop_tpu.serve.placement import dgro_place

    toks, owns, rep = dgro_place(SERVERS, 50, candidates=6, probes=1 << 13,
                                 churn_frac=0.05, seed=2)
    assert rep["movement_chosen"] <= rep["movement_random"] + 1e-9
    assert all(e == 0.0 for e in rep["excess_movement"])
    assert toks.shape == owns.shape == (len(SERVERS) * 50,)
    assert list(toks) == sorted(toks)


def test_dgro_sticky_replay_is_bit_identical():
    from ringpop_tpu.serve.placement import dgro_place

    toks, owns, rep = dgro_place(SERVERS[:8], 20, candidates=4, probes=1 << 12)
    toks2, owns2, rep2 = dgro_place(SERVERS[:8], 20, fixed_salt=rep["salt"])
    assert np.array_equal(toks, toks2) and np.array_equal(owns, owns2)
    assert not rep2["rescored"]


def test_dgro_store_serves_correctly_and_stays_sticky():
    store = RingStore(SERVERS[:12], replica_points=20, placement="dgro",
                      placement_kw=dict(probes=1 << 12, candidates=4))
    salt = store._dgro_salt
    probe = _hashes(256, seed=17)
    ring, gen, _ = store.snapshot()
    dev = np.asarray(serve_lookup(ring, jnp.asarray(probe))[0])
    ht, ho, hg, _hns = store.snapshot_host()
    idx = np.searchsorted(ht, probe, side="left")
    assert np.array_equal(dev, ho[np.where(idx == ht.shape[0], 0, idx)])
    # membership churn must replay the SAME candidate (sticky salt)
    store.update(add=["sticky:1"])
    assert store._dgro_salt == salt
    ring2, gen2, _ = store.snapshot()
    assert gen2 == 1


def test_dgro_local_move_family_diameter_guided():
    """The r17 widened family: local-move candidates exist alongside the
    salt re-mixes, each strictly shrinks the default placement's ring
    diameter (that is what the moves are FOR), keeps churn movement at
    candidate 0's level (sticky overrides — replay moves nothing), and
    stays consistent-hashing-clean (zero excess)."""
    from ringpop_tpu.serve.placement import dgro_place

    toks, owns, rep = dgro_place(SERVERS, 50, candidates=4,
                                 local_moves=(2, 4, 8), probes=1 << 13,
                                 churn_frac=0.05, seed=2)
    assert rep["family"] == 7 and rep["move_candidates"] == 3
    d0 = rep["diameter"][0]
    m0 = rep["movement"][0]
    for c in range(4, 7):  # the move candidates ride after the salts
        assert rep["diameter"][c] < d0
        assert rep["movement"][c] <= m0 + 1e-9  # gate-eligible
        assert rep["excess_movement"][c] == 0.0
    # more moves -> no larger diameter (monotone guidance)
    assert rep["diameter"][6] <= rep["diameter"][4]
    assert rep["movement_chosen"] <= rep["movement_random"] + 1e-9


def test_dgro_local_move_sticky_replay_and_store_churn():
    """A chosen move candidate replays bit-identically through
    (fixed_salt, fixed_moves), and a RingStore under membership churn
    keeps every surviving override's token value unchanged."""
    from ringpop_tpu.serve.placement import dgro_place

    servers = SERVERS[:8]
    toks, owns, rep = dgro_place(servers, 20, candidates=1,
                                 local_moves=(4,), probes=1 << 12, seed=5)
    # candidates=1 leaves only the default + the move variant; the move
    # variant wins on diameter at equal movement
    assert rep["local_moves"] == 4 and len(rep["moves"]) == 4
    t2, o2, rep2 = dgro_place(servers, 20, fixed_salt=rep["salt"],
                              fixed_moves=rep["moves"])
    assert np.array_equal(toks, t2) and np.array_equal(owns, o2)
    assert not rep2["rescored"]

    store = RingStore(servers, replica_points=20, placement="dgro",
                      placement_kw=dict(candidates=1, local_moves=(4,),
                                        probes=1 << 12, seed=5))
    moves = dict(store._dgro_moves)
    assert moves == rep["moves"]
    store.update(add=["mv:1"], remove=[servers[0]])
    assert store._dgro_moves == moves  # sticky across churn
    ht, ho, hg, _ = store.snapshot_host()
    surviving = {k: v for k, v in moves.items() if k[0] != servers[0]}
    for (_srv, _rep), tok in surviving.items():
        assert np.uint32(tok) in ht  # survivor overrides kept verbatim


def test_dgro_candidate_zero_is_default_placement():
    """Salt 0 must reproduce the reference random-replica placement
    exactly — the gate's baseline is the real baseline."""
    from ringpop_tpu.serve.placement import dgro_place
    from ringpop_tpu.ops.ring_ops import build_ring_tokens

    toks, owns, _rep = dgro_place(SERVERS[:6], 30, fixed_salt=0)
    ref_t, ref_o = build_ring_tokens(sorted(SERVERS[:6]), 30)
    assert np.array_equal(toks, np.asarray(ref_t))
    assert np.array_equal(owns, np.asarray(ref_o))


def test_key_movement_metric():
    """Removing one server moves exactly its keys: moved_frac equals the
    removed load share and excess_moved (consistent-hashing violations)
    is zero."""
    from ringpop_tpu.ops.ring_ops import build_ring_tokens
    from ringpop_tpu.serve.placement import key_movement

    a = sorted(SERVERS[:10])
    b = sorted(SERVERS[1:10])  # drop one
    ta, oa = build_ring_tokens(a, 50)
    tb, ob = build_ring_tokens(b, 50)
    hashes = jnp.asarray(_hashes(1 << 14, seed=19))
    rep = key_movement(ta, oa, a, tb, ob, b, hashes)
    assert rep["excess_moved"] == 0
    assert rep["moved_frac"] == rep["removed_load_frac"]
    assert 0.02 < rep["moved_frac"] < 0.3


# -- review-fix pins ----------------------------------------------------------


def test_hashring_add_and_remove_same_server_one_batch():
    """A server in BOTH lists of one batch (a flapping node in one SWIM
    membership update) is a net no-op for the arrays — the incremental
    path must not crash on it (regression: KeyError in the merge-insert)."""
    ring = HashRing(replica_points=10)
    ring.add_remove_servers(["a:1", "b:1"], [])
    assert ring.add_remove_servers(["c:1"], ["c:1"])  # event still fires
    oracle = HashRing(replica_points=10)
    oracle.add_remove_servers(["a:1", "b:1"], [])
    assert np.array_equal(ring._tokens, oracle._tokens)
    assert np.array_equal(ring._owners, oracle._owners)
    assert ring.checksum() == oracle.checksum()


def test_snapshot_survives_one_concurrent_commit():
    """The ping-pong donation contract: a DeviceRing snapshot taken before
    a commit still answers (correctly, at ITS generation) after that
    commit — commit N donates generation N-2's buffers, never N-1's."""
    store = RingStore(SERVERS, replica_points=10)
    old_ring, old_gen, _ = store.snapshot()
    old_servers = store.servers_at(old_gen)
    store.update(add=["race:1"])  # one concurrent commit
    probe = _hashes(128, seed=21)
    owners, gen = serve_lookup(old_ring, jnp.asarray(probe))
    assert int(np.asarray(gen)[0]) == old_gen
    oracle = HostBisectFrontend(old_servers, 10).lookup_hashes(probe)
    assert np.array_equal(np.asarray(owners), oracle)
    # ...and two commits later the OLD snapshot's buffers are donated
    # (that tail is what the service's dispatch retry covers)
    store.update(add=["race:2"])
    new_ring, new_gen, _ = store.snapshot()
    fresh = np.asarray(serve_lookup(new_ring, jnp.asarray(probe))[0])
    oracle2 = HostBisectFrontend(store.servers_at(new_gen), 10).lookup_hashes(probe)
    assert np.array_equal(fresh, oracle2)


def test_flush_retries_on_retired_ring(monkeypatch):
    """Double-commit-mid-dispatch tail: the first dispatch attempt hitting
    a deleted donated buffer must refetch the newest snapshot and answer
    from it — requests resolve, nothing strands."""
    import ringpop_tpu.serve.service as svc_mod

    store = RingStore(SERVERS, replica_points=10)
    svc = RingService(store, flush_us=0.0)
    real = svc_mod.serve_lookup_fused
    calls = {"n": 0}

    def flaky(ring, hashes):
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("Array has been deleted with shape=uint32[6400]")
        return real(ring, hashes)

    monkeypatch.setattr(svc_mod, "serve_lookup_fused", flaky)
    h = _hashes(32, seed=23)

    async def main():
        return await asyncio.wait_for(svc.submit(h), timeout=10)

    owners, gen = asyncio.run(main())
    assert calls["n"] == 2  # one retry
    oracle = HostBisectFrontend(SERVERS, 10).lookup_hashes(h)
    assert np.array_equal(owners, oracle)


def test_flush_failure_fails_futures_not_hangs(monkeypatch):
    """A non-retryable dispatch error must surface on the future (the TCP
    client sees an error response), never strand it pending."""
    import ringpop_tpu.serve.service as svc_mod

    store = RingStore(SERVERS, replica_points=10)
    svc = RingService(store, flush_us=0.0)

    def broken(ring, hashes):
        raise ValueError("boom")

    monkeypatch.setattr(svc_mod, "serve_lookup_fused", broken)

    async def main():
        with pytest.raises(ValueError):
            await asyncio.wait_for(svc.submit(_hashes(8)), timeout=10)

    asyncio.run(main())


def test_shm_slot_not_poisoned_by_dispatch_error(service_thread, monkeypatch):
    """A dispatch exception answers STATUS_ERR (client raises) and frees
    the slot — the NEXT request on the same slot must succeed."""
    import ringpop_tpu.serve.service as svc_mod

    from ringpop_tpu.serve.shm import ShmClient

    th = service_thread
    real = svc_mod.serve_lookup_fused
    fail_once = {"armed": True}

    def flaky(ring, hashes):
        if fail_once["armed"]:
            fail_once["armed"] = False
            raise ValueError("injected dispatch failure")
        return real(ring, hashes)

    monkeypatch.setattr(svc_mod, "serve_lookup_fused", flaky)
    name, sock, slots, cap, max_n = th.shm_address()
    out = {}

    def client_run():
        cl = ShmClient(name, sock, 0, slots=slots, key_cap=cap, max_n=max_n)
        big = _hashes(600, seed=27)  # >64: rides the collector, hits flaky
        try:
            cl.lookup_hashes(big)
            out["first"] = "ok"
        except RuntimeError:
            out["first"] = "error"
        out["second"] = cl.lookup_hashes(big)  # slot must still work
        cl.close()

    t = threading.Thread(target=client_run)
    t.start()
    t.join(timeout=60)
    assert not t.is_alive()
    assert out["first"] == "error"
    owners, gen = out["second"]
    oracle = HostBisectFrontend(SERVERS, 10).lookup_hashes(_hashes(600, seed=27))
    assert np.array_equal(owners, oracle)


def test_service_chains_existing_on_update_hook():
    """RingService must not silently replace a caller-installed
    RingStore(on_update=...) hook — both must fire per generation."""
    seen = []
    store = RingStore(SERVERS[:4], replica_points=5, on_update=seen.append)
    journal = _Journal()
    RingService(store, journal=journal)
    store.update(add=["hooked:1"])
    assert len(seen) == 1 and seen[0]["gen"] == 1  # caller hook still fires
    assert journal.records[-1]["kind"] == "ring_update"  # service journal too
