"""Multi-host serve mesh (serve/mesh.py, r17): block ownership,
cross-forwarded LookupN answering over the fabric, digest certificates."""

import numpy as np
import pytest

from ringpop_tpu.serve.mesh import (
    ServeMesh,
    _digest_chain,
    _stream_hashes,
    run_serve_mesh,
)

CFG = dict(n_servers=8, replica_points=20, rounds=2, keys_per_stream=256, seed=3)


def _oracle_digest(n_servers, replica_points, n, streams, rounds,
                   keys_per_stream, seed, gen=0):
    """The single-process oracle computed OUTSIDE the mesh entirely: the
    host LookupNUniqueAt walk per key, digest-chained per stream."""
    from ringpop_tpu.hashing import fingerprint32
    from ringpop_tpu.ops.ring_ops import build_ring_tokens, host_lookup_n

    servers = [f"10.21.{i // 256}.{i % 256}:3000" for i in range(n_servers)]
    toks, owns = build_ring_tokens(servers, replica_points)
    tokens = np.asarray(toks, np.uint32)
    owners = np.asarray(owns, np.int32)
    digests = {}
    for s in range(streams):
        d = 0
        for rnd in range(rounds):
            hashes = _stream_hashes(seed, s, rnd, keys_per_stream)
            rows = host_lookup_n(tokens, owners, hashes, n, n_servers)
            d = _digest_chain(d, hashes, rows, gen)
        digests[s] = d
    return fingerprint32(
        b"".join(digests[s].to_bytes(4, "little") for s in range(streams))
    )


def test_mesh_p1_matches_host_walk_oracle():
    """P=1 (no forwarding at all) must reproduce the pure host-walk
    oracle digest — pins the fused device dispatch end-to-end."""
    recs = run_serve_mesh(1, n=3, streams=4, **CFG)
    want = _oracle_digest(CFG["n_servers"], CFG["replica_points"], 3, 4,
                          CFG["rounds"], CFG["keys_per_stream"], CFG["seed"])
    assert recs[0]["digest"] == want


@pytest.mark.parametrize("nprocs", [2, 4])
def test_mesh_digest_equals_single_process_oracle(nprocs):
    """The tentpole certificate: every (owner, successors, generation)
    tuple answered by the P-rank mesh digests equal to the P=1 oracle."""
    oracle = run_serve_mesh(1, n=3, streams=4, **CFG)[0]["digest"]
    recs = run_serve_mesh(nprocs, n=3, streams=4, **CFG)
    for rec in recs:
        assert rec["digest"] == oracle
        # and every rank agrees on every stream digest
        assert rec["stream_digests"] == recs[0]["stream_digests"]


def test_mesh_message_count_is_o_owners_not_o_keys():
    recs = run_serve_mesh(2, n=3, streams=4, **CFG)
    for rec in recs:
        # 2 legs x (P-1) peers x rounds, regardless of key volume
        assert rec["messages_sent"] == 2 * 1 * CFG["rounds"]
        assert rec["keys_forwarded_out"] > rec["messages_sent"]
        assert rec["messages_sent"] < rec["messages_naive"]


def test_mesh_wire_accounting_contract():
    """P=1 moves zero wire bytes; P>1 records split wire/raw counters
    with wire <= raw (the codec may only ever shrink)."""
    rec1 = run_serve_mesh(1, n=3, streams=4, **CFG)[0]
    assert rec1["wire"]["bytes_sent"] == 0
    for rec in run_serve_mesh(4, n=3, streams=4, **CFG):
        w = rec["wire"]
        assert w["bytes_sent"] > 0 and w["bytes_recv"] > 0
        assert w["bytes_sent"] <= w["raw_bytes_sent"]
        assert w["bytes_recv"] <= w["raw_bytes_recv"]


def test_mesh_block_ownership_covers_ring_exactly():
    """The process_block rule over the token index space: blocks tile
    [0, count) contiguously, and rank_of_hashes lands every key inside
    the owning rank's block."""
    from ringpop_tpu.forward.batch import rank_of_hashes
    from ringpop_tpu.ops.ring_ops import build_ring_tokens
    from ringpop_tpu.parallel.partition import process_block

    servers = [f"10.21.0.{i}:3000" for i in range(8)]
    toks, _ = build_ring_tokens(servers, 20)
    tokens = np.asarray(toks, np.uint32)
    count = tokens.shape[0]
    blocks = [process_block(count, r, 4) for r in range(4)]
    assert blocks[0][0] == 0 and blocks[-1][1] == count
    for (_, hi), (lo, _) in zip(blocks, blocks[1:]):
        assert hi == lo
    rng = np.random.default_rng(0)
    hashes = rng.integers(0, 2**32, size=512, dtype=np.uint32)
    ranks = rank_of_hashes(tokens, hashes, 4)
    idx = np.searchsorted(tokens, hashes, side="left")
    idx = np.where(idx >= count, 0, idx)
    for h_idx, r in zip(idx, ranks):
        lo, hi = blocks[r]
        assert lo <= h_idx < hi


def test_mesh_refuses_non_divisible_workload_and_ring():
    with pytest.raises(ValueError):
        run_serve_mesh(3, n=3, streams=4, **CFG)  # streams % P != 0
    # token count must divide too (process_block's rigidity): 8*20=160
    # tokens over 7 ranks — refused loudly at construction
    from ringpop_tpu.parallel.fabric import LocalKV

    with pytest.raises(ValueError):
        ServeMesh(0, 7, [f"s{i}:1" for i in range(8)], replica_points=20,
                  streams=7, kv=LocalKV())


def test_mesh_codec_off_digest_identical():
    """The r15 codec is transport-transparent: codec-off mesh answers the
    same digests (the wire may only cost more)."""
    on = run_serve_mesh(2, n=3, streams=4, codec=True, **CFG)
    off = run_serve_mesh(2, n=3, streams=4, codec=False, **CFG)
    assert on[0]["digest"] == off[0]["digest"]
    for a, b in zip(on, off):
        assert a["wire"]["bytes_sent"] <= b["wire"]["bytes_sent"]


def test_mesh_digests_bit_identical_with_spans_enabled():
    """r20: span tracing is host-plane only — a traced P=2 mesh run
    lands the SAME per-rank digests as the untraced twin AND the P=1
    oracle, while the span records themselves join across ranks (every
    mesh_answer's computed parent is an emitted mesh_request span of
    the same trace, generation attached)."""
    records = []
    base = run_serve_mesh(2, n=3, streams=4, **CFG)
    traced = run_serve_mesh(
        2, n=3, streams=4, trace_sink=records.append, trace_sample=16, **CFG
    )
    oracle = run_serve_mesh(1, n=3, streams=4, **CFG)[0]["digest"]
    assert {r["digest"] for r in base} == {oracle}
    assert {r["digest"] for r in traced} == {oracle}
    reqs = {r["span"]: r for r in records if r["leg"] == "mesh_request"}
    answers = [r for r in records if r["leg"] == "mesh_answer"]
    assert reqs and answers, "sampled keys must have produced both legs"
    for a in answers:
        mate = reqs.get(a["parent"])
        assert mate is not None, "answer span parent not an emitted request"
        assert mate["trace"] == a["trace"]
        assert a["gen"] == 0  # the mesh runs at generation 0
        # opposite directions of the same peer pair in the same round
        assert (mate["rank"], mate["dest"]) == (a["src"], a["rank"])
        assert mate["rnd"] == a["rnd"]
