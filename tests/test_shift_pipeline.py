"""r11 exchange-pipelining certificates (parallel/shift.py).

Pins, on the virtual 8-device mesh:

* the mod-n shift contract — ``shard_roll`` now accepts any int32 shift
  (>= n, negative) and matches ``jnp.roll`` exactly (the r8 version was
  only pinned on [0, n));
* the sub-block factor H as a parameter: H ∈ {2, 4} sweeps bit-identical
  to ``jnp.roll``, with the (H+1)-sends-per-rolled-leaf-per-leg census
  floor visible in the traced program, and the historical fallback to
  H=1 when H does not divide the shard block;
* ``shard_roll_pipelined`` — the fused two-leg region — bit-identical to
  the sequential composition (roll, merge, roll back) over an exhaustive
  shift sweep, and at engine level: the pipelined lifecycle/delta steps
  land bit-equal to the sequential-leg steps tick for tick.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from ringpop_tpu.parallel.shift import shard_roll, shard_roll_pipelined
from ringpop_tpu.sim import delta, lifecycle
from ringpop_tpu.sim.delta import DeltaFaults


@pytest.fixture(scope="module")
def mesh():
    devs = np.asarray(jax.devices("cpu")[:8]).reshape(4, 2)
    return Mesh(devs, ("node", "rumor"))


def _planes(n=64, w=4):
    x = jnp.arange(n * w, dtype=jnp.uint32).reshape(n, w)
    v = jnp.arange(n, dtype=jnp.int32) * 7
    learned = x ^ jnp.uint32(0xA5A5)
    ride = (x * jnp.uint32(2654435761)) | jnp.uint32(1)
    return x, v, learned, ride


WSPEC, VSPEC = P("node", "rumor"), P("node")


@pytest.mark.parametrize("h", [2, 4])
def test_shard_roll_mod_n_contract(mesh, h):
    """Shifts >= n, negative, and multiples of n all follow jnp.roll's
    mod-n semantics — the contract tests used to leave unpinned."""
    x, v, _, _ = _planes()
    n = x.shape[0]
    roll = jax.jit(
        lambda x, v, s: shard_roll((x, v), s, mesh, "node", (WSPEC, VSPEC), h=h)
    )
    for s in [0, 1, n - 1, n, n + 3, 2 * n, 2 * n + 5, -1, -n, -n - 7, 3 * n + 3]:
        a, b = roll(x, v, jnp.int32(s))
        assert bool((a == jnp.roll(x, s, axis=0)).all()), (h, s)
        assert bool((b == jnp.roll(v, s, axis=0)).all()), (h, s)


@pytest.mark.parametrize("h", [2, 4])
def test_shard_roll_h_sweep_bit_identity(mesh, h):
    """Every shift class of the H decomposition matches jnp.roll."""
    x, v, _, _ = _planes()
    n = x.shape[0]
    roll = jax.jit(
        lambda x, v, s: shard_roll((x, v), s, mesh, "node", (WSPEC, VSPEC), h=h)
    )
    for s in range(n):
        a, b = roll(x, v, jnp.int32(s))
        assert bool((a == jnp.roll(x, s, axis=0)).all()), (h, s)
        assert bool((b == jnp.roll(v, s, axis=0)).all()), (h, s)


def _branch_ppermute_counts(closed) -> list:
    """Per-switch-branch ppermute counts of a traced program."""
    from ringpop_tpu.analysis.trace_checks import _sub_jaxprs

    def count(jaxpr):
        c = 0
        for eqn in jaxpr.eqns:
            if eqn.primitive.name == "ppermute":
                c += 1
            for sub in _sub_jaxprs(eqn):
                c += count(sub)
        return c

    counts = []

    def rec(jaxpr):
        for eqn in jaxpr.eqns:
            if eqn.primitive.name == "cond":
                for br in eqn.params["branches"]:
                    counts.append(count(br.jaxpr))
            else:
                for sub in _sub_jaxprs(eqn):
                    rec(sub)

    rec(closed.jaxpr)
    return counts


@pytest.mark.parametrize("h", [2, 4])
def test_send_count_is_h_plus_one_per_leg(mesh, h):
    """The census floor of the decomposition: every switch branch sends
    at most H+1 ppermutes per rolled leaf, and the worst branch sends
    exactly H+1 (one per window sub-block; self-sends skipped)."""
    x, _, _, _ = _planes()
    closed = jax.make_jaxpr(
        lambda x, s: shard_roll((x,), s, mesh, "node", (WSPEC,), h=h)
    )(x, jnp.int32(3))
    counts = _branch_ppermute_counts(closed)
    s_shards = mesh.shape["node"]
    assert len(counts) == h * s_shards  # one branch per quotient class
    assert max(counts) == h + 1
    assert all(c <= h + 1 for c in counts)


def test_h_fallback_when_not_dividing(mesh):
    """H that does not divide the shard block falls back to 1 (the
    historical odd-block behavior) instead of mis-slicing."""
    x, v, _, _ = _planes(n=40)  # nb = 10, not divisible by 4
    roll = jax.jit(
        lambda x, v, s: shard_roll((x, v), s, mesh, "node", (WSPEC, VSPEC), h=4)
    )
    for s in [0, 3, 17, 39, 41, -2]:
        a, b = roll(x, v, jnp.int32(s))
        assert bool((a == jnp.roll(x, s, axis=0)).all()), s
    closed = jax.make_jaxpr(
        lambda x, s: shard_roll((x,), s, mesh, "node", (WSPEC,), h=4)
    )(x, jnp.int32(3))
    counts = _branch_ppermute_counts(closed)
    assert max(counts) == 2  # H=1 ⇒ H+1 = 2 sends per leaf


@pytest.mark.parametrize("h", [2, 4])
def test_pipelined_matches_sequential_composition(mesh, h):
    """Exhaustive shift sweep: the fused two-leg region's outputs equal
    the sequential composition roll → elementwise merge → roll back,
    bit for bit, in every (quotient, remainder==0) branch class."""
    x, v, learned, ride = _planes()
    n = x.shape[0]

    def leg2(inb, gp, lrn, rd):
        return (lrn | inb) & rd

    pipe = jax.jit(
        lambda x, v, l, r, s: shard_roll_pipelined(
            (x, v), s, mesh, "node", (WSPEC, VSPEC),
            carry=(l, r), carry_specs=(WSPEC, WSPEC),
            leg2_of=leg2, spec2=WSPEC, h=h,
        )
    )
    for s in list(range(n)) + [n, n + 5, -3, 2 * n + 1]:
        a, b, resp = pipe(x, v, learned, ride, jnp.int32(s))
        ra = jnp.roll(x, s, axis=0)
        assert bool((a == ra).all()), (h, s)
        assert bool((b == jnp.roll(v, s, axis=0)).all()), (h, s)
        exp = jnp.roll((learned | ra) & ride, -s, axis=0)
        assert bool((resp == exp).all()), (h, s)


@pytest.mark.parametrize("engine", ["lifecycle", "delta"])
@pytest.mark.parametrize("h", [2, 4])
def test_engine_pipelined_bit_equal_to_sequential(mesh, engine, h):
    """Engine level: the pipelined exchange steps land bit-equal to the
    sequential r8 legs tick for tick (fresh shift class per tick), for
    both engines and both H settings."""
    n, k = 4096, 64
    if engine == "lifecycle":
        base = lifecycle.LifecycleParams(
            n=n, k=k, suspect_ticks=10, rng="counter",
            exchange_mesh=mesh, exchange_h=h,
        )
        state = jax.tree.map(
            jax.device_put,
            lifecycle.init_state(base, seed=0),
            lifecycle.state_shardings(mesh, k=k),
        )
        step_fn = lifecycle.step
    else:
        from ringpop_tpu.parallel.mesh import shard_delta_state

        base = delta.DeltaParams(
            n=n, k=k, rng="counter", exchange_mesh=mesh, exchange_h=h
        )
        state = shard_delta_state(delta.init_state(base, seed=0), mesh)
        step_fn = delta.step
    up = np.ones(n, bool)
    up[::64] = False
    faults = DeltaFaults(up=jnp.asarray(up))
    pipe = jax.jit(functools.partial(
        step_fn, dataclasses.replace(base, exchange_pipelined=True)))
    seq = jax.jit(functools.partial(
        step_fn, dataclasses.replace(base, exchange_pipelined=False)))
    st = state
    for _ in range(6):
        a = pipe(st, faults)
        b = seq(st, faults)
        for la, lb in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
            assert bool((np.asarray(la) == np.asarray(lb)).all())
        st = a
