"""Sim-plane tests: full-view protocol semantics, delta dissemination,
fault models, mesh sharding, ring ops (all on the CPU backend from
conftest; the 8-device mesh exercises the sharded path)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ringpop_tpu.sim.delta import (
    DeltaFaults,
    DeltaParams,
    DeltaSim,
    init_state as delta_init,
    run_until_converged,
)
from ringpop_tpu.sim.fullview import Faults, FullViewParams, FullViewSim, init_state, step
from ringpop_tpu.swim.member import ALIVE, FAULTY, SUSPECT, TOMBSTONE


class TestFullView:
    def test_converged_cluster_is_stable(self):
        sim = FullViewSim(16, seed=0)
        sim.run(8)
        assert sim.views_converged()
        assert not sim.has_changes()
        assert (sim.status_matrix() == ALIVE).all()

    def test_dead_node_detected_and_marked_faulty(self):
        n = 16
        up = np.ones(n, dtype=bool)
        up[5] = False
        faults = Faults(up=jnp.asarray(up))
        sim = FullViewSim(n, seed=1, suspect_ticks=5)
        sim.run(40, faults)
        sm = sim.status_matrix()
        for i in range(n):
            if i != 5:
                assert sm[i, 5] == FAULTY

    def test_suspect_is_refuted_when_node_is_alive(self):
        n = 12
        sim = FullViewSim(n, seed=2, suspect_ticks=50)
        # declare node 3 suspect at node 0 by fiat
        from ringpop_tpu.sim import fullview as fv

        st = sim.state
        key = (st.incarnation[0, 3].astype(jnp.int32) << 3) | SUSPECT
        cand = jnp.full((n, n), -1, jnp.int32).at[0, 3].set(key)
        sim.state, _ = fv._apply_batch(
            sim.params, st, cand, cand >= 0, jnp.int32(1), jnp.eye(n, dtype=bool)
        )
        assert sim.status_matrix()[0, 3] == SUSPECT

        sim.run(60)
        sm = sim.status_matrix()
        inc = np.asarray(sim.state.incarnation)
        assert (sm[:, 3] == ALIVE).all()
        assert inc[3, 3] > 0  # reincarnated
        assert sim.views_converged()

    def test_suspect_faulty_tombstone_evict_chain(self):
        n = 8
        up = np.ones(n, dtype=bool)
        up[2] = False
        faults = Faults(up=jnp.asarray(up))
        sim = FullViewSim(n, seed=3, suspect_ticks=3, faulty_ticks=5, tombstone_ticks=5)
        sim.run(60, faults)
        present = np.asarray(sim.state.present)
        for i in range(n):
            if i != 2:
                assert not present[i, 2]  # evicted everywhere

    def test_partition_blocks_dissemination_then_heals(self):
        n = 12
        group = np.zeros(n, dtype=np.int32)
        group[n // 2 :] = 1
        parted = Faults(group=jnp.asarray(group))
        sim = FullViewSim(n, seed=4, suspect_ticks=1000)  # no state churn
        # inject a rumor on side 0: node 0 reincarnates itself
        from ringpop_tpu.sim import fullview as fv

        st = sim.state
        key = ((st.incarnation[0, 0] + 200).astype(jnp.int32) << 3) | ALIVE
        cand = jnp.full((n, n), -1, jnp.int32).at[0, 0].set(key)
        sim.state, _ = fv._apply_batch(
            sim.params, st, cand, cand >= 0, jnp.int32(1), jnp.eye(n, dtype=bool)
        )
        sim.run(40, parted)
        inc = np.asarray(sim.state.incarnation)
        side_a = range(n // 2)
        side_b = range(n // 2, n)
        assert all(inc[i, 0] > 0 for i in side_a)  # spread within partition
        assert all(inc[i, 0] == 0 for i in side_b)  # blocked by partition

        sim.run(40)  # partition heals (no faults)
        inc = np.asarray(sim.state.incarnation)
        assert all(inc[i, 0] > 0 for i in range(n))

    def test_packet_loss_on_all_probe_legs(self):
        """drop_rate>0 exercises loss on the direct ping AND both indirect
        ping-req legs; heavy loss must still detect a dead node and never
        wedge a live cluster in a non-alive view."""
        n = 16
        sim = FullViewSim(n, seed=9, suspect_ticks=6)
        up = np.ones(n, bool)
        up[5] = False
        faults = Faults(up=jnp.asarray(up), drop_rate=0.3)
        sim.run(120, faults)
        sm = sim.status_matrix()
        live = [i for i in range(n) if i != 5]
        assert (sm[live, 5] >= FAULTY).all()
        # with loss gone, any spurious suspicions get refuted
        sim.run(80, Faults(up=jnp.asarray(up)))
        sm = sim.status_matrix()
        assert (sm[np.ix_(live, live)] == ALIVE).all()

    def test_deterministic_given_seed(self):
        a = FullViewSim(10, seed=7)
        b = FullViewSim(10, seed=7)
        a.run(10)
        b.run(10)
        assert (a.status_matrix() == b.status_matrix()).all()
        assert (np.asarray(a.state.incarnation) == np.asarray(b.state.incarnation)).all()

    def test_injected_targets_for_lockstep_runs(self):
        n = 6
        params = FullViewParams(n=n)
        st = init_state(params, seed=0)
        targets = jnp.asarray([1, 2, 3, 4, 5, 0], dtype=jnp.int32)
        out = step(params, st, Faults(), targets=targets)
        assert int(out.tick) == 1


class TestDelta:
    def test_rumors_converge(self):
        sim = DeltaSim(512, 32, seed=0)
        ticks, ok = sim.run_until_converged()
        assert ok and ticks <= 64

    def test_convergence_under_packet_loss(self):
        # BASELINE config: 5% loss
        sim = DeltaSim(512, 32, seed=1)
        ticks, ok = sim.run_until_converged(DeltaFaults(drop_rate=0.05))
        assert ok

    def test_partition_blocks_then_heals(self):
        n, k = 256, 16
        group = np.zeros(n, dtype=np.int32)
        group[n // 2 :] = 1
        sim = DeltaSim(n, k, seed=2)
        # all rumors start on side 0
        sim.state = delta_init(sim.params, seed=2, sources=np.zeros(k, dtype=np.int64))
        parted = DeltaFaults(group=jnp.asarray(group))
        for _ in range(64):
            sim.tick(parted)
        from ringpop_tpu.sim.packbits import unpack_bits

        learned = np.asarray(unpack_bits(sim.state.learned, k))
        assert learned[: n // 2].all()  # side 0 fully infected
        assert not learned[n // 2 :].any()  # side 1 isolated

        # heal: rumors cross over. piggyback counters on side 0 may have
        # expired (maxP bound) — the healed cluster still converges because
        # side-1 learners re-disseminate with fresh counters
        ticks, ok = sim.run_until_converged(max_ticks=512)
        assert ok

    def test_both_exchange_topologies_converge(self):
        """shift (scatterless cyclic partners) and uniform (independent
        draws) give the same epidemic behavior."""
        for exch in ("shift", "uniform"):
            sim = DeltaSim(512, 32, seed=4, exchange=exch)
            ticks, ok = sim.run_until_converged()
            assert ok and ticks <= 64, (exch, ticks)

    def test_max_p_bounds_dissemination_traffic(self):
        # a rumor stops riding after maxP propagations per node
        sim = DeltaSim(64, 4, seed=3, max_p=2)
        for _ in range(50):
            sim.tick()
        # counters are capped at max_p
        assert int(np.asarray(sim.state.pcount).max()) <= 2

    def test_dead_nodes_do_not_block_convergence_check(self):
        n = 128
        up = np.ones(n, dtype=bool)
        up[50] = False  # dead node is NOT a rumor source (sources are 0..7)
        faults = DeltaFaults(up=jnp.asarray(up))
        sim = DeltaSim(n, 8, seed=4)
        ticks, ok = sim.run_until_converged(faults)
        assert ok  # converged over LIVE nodes
        from ringpop_tpu.sim.packbits import unpack_bits

        assert not bool(np.asarray(unpack_bits(sim.state.learned, 8))[50].all())


class TestMeshSharding:
    def test_sharded_step_matches_single_device(self):
        from ringpop_tpu.parallel.mesh import make_mesh, shard_delta_state, sharded_delta_step

        # k=64 -> packed learned is uint32[N, 2]: one word per rumor shard
        params = DeltaParams(n=64, k=64)
        state = delta_init(params, seed=5)
        mesh = make_mesh(8)
        sharded = shard_delta_state(state, mesh)
        step_fn = sharded_delta_step(params, mesh)
        out_sharded = step_fn(sharded)

        from ringpop_tpu.sim.delta import step as plain_step

        out_plain = jax.jit(lambda s: plain_step(params, s))(state)
        assert (np.asarray(out_sharded.learned) == np.asarray(out_plain.learned)).all()
        assert (np.asarray(out_sharded.pcount) == np.asarray(out_plain.pcount)).all()

    def test_mesh_shapes(self):
        from ringpop_tpu.parallel.mesh import make_mesh

        mesh = make_mesh(8)
        assert mesh.shape["node"] * mesh.shape["rumor"] == 8

    def test_rumor_shard_rule(self):
        """The shared guard rejects every k the packed planes cannot place:
        not just k < 32*shards but any k whose WORD count (or slot
        alignment) does not divide the rumor axis — k=96 over 2 shards is
        the advisor's counterexample (3 words, 2 shards)."""
        import pytest

        from ringpop_tpu.parallel.mesh import make_mesh, sharded_delta_step
        from ringpop_tpu.sim.lifecycle import state_shardings
        from ringpop_tpu.sim.packbits import check_rumor_shardable

        check_rumor_shardable(64, 2)  # fine: one word per shard
        check_rumor_shardable(96, 1)  # fine: unsharded rumor axis
        for k, shards in ((96, 2), (48, 2), (33, 2), (64, 4)):
            with pytest.raises(ValueError, match="multiple of 32"):
                check_rumor_shardable(k, shards)

        mesh = make_mesh(8)  # (4, 2) by default
        with pytest.raises(ValueError, match="multiple of 32"):
            sharded_delta_step(DeltaParams(n=64, k=96), mesh)
        with pytest.raises(ValueError, match="multiple of 32"):
            state_shardings(mesh, k=96)


class TestRingOps:
    def test_device_lookup_matches_host_ring(self):
        from ringpop_tpu.hashing.farm import fingerprint32_batch, pack_strings
        from ringpop_tpu.hashring import HashRing
        from ringpop_tpu.ops import build_ring_tokens, ring_lookup, ring_lookup_n

        servers = sorted(f"10.0.1.{i}:3000" for i in range(12))
        r = HashRing()
        r.add_remove_servers(servers, [])
        toks, owners = build_ring_tokens(servers, 100)

        keys = [f"key-{i}" for i in range(500)]
        mat, lens = pack_strings(keys)
        hashes = jnp.asarray(fingerprint32_batch(mat, lens))

        got = np.asarray(ring_lookup(toks, owners, hashes))
        want = np.array([servers.index(r.lookup(k)) for k in keys])
        assert (got == want).all()

        got_n = np.asarray(ring_lookup_n(toks, owners, hashes[:64], 3, len(servers)))
        want_n = np.array([[servers.index(s) for s in r.lookup_n(k, 3)] for k in keys[:64]])
        assert (got_n == want_n).all()

    @pytest.mark.parametrize("replica_points", [1, 3, 100])
    @pytest.mark.parametrize("n_servers", [1, 2, 3, 5, 17])
    def test_lookup_n_exact_vs_host_adversarial(self, replica_points, n_servers):
        """Exactness property (VERDICT round-1 item 7): the device walk must
        equal the host ring's exact walk (rbtree.go:262-288 semantics) for
        every (replica_points, server-count) combination — including rings
        with FEWER replica slots than the scan window, where the old bounded
        window could return short rows, and n > num_servers (-1 padding)."""
        from ringpop_tpu.hashring import HashRing
        from ringpop_tpu.ops import build_ring_tokens, ring_lookup_n

        servers = sorted(f"10.7.{i // 256}.{i % 256}:3000" for i in range(n_servers))
        r = HashRing(replica_points=replica_points)
        r.add_remove_servers(servers, [])
        toks, owners = build_ring_tokens(servers, replica_points)

        # adversarial hashes: exact token values, their neighbors, the ring
        # wraparound extremes, plus uniform randoms
        tok_np = np.asarray(toks, dtype=np.uint64)
        rng = np.random.default_rng(replica_points * 1000 + n_servers)
        hs = np.unique(
            np.concatenate(
                [
                    tok_np,
                    (tok_np - 1) & 0xFFFFFFFF,
                    (tok_np + 1) & 0xFFFFFFFF,
                    np.array([0, 1, 2**32 - 1], dtype=np.uint64),
                    rng.integers(0, 2**32, size=200, dtype=np.uint64),
                ]
            )
        ).astype(np.uint32)

        for n in (1, 3, n_servers, n_servers + 2):
            got = np.asarray(ring_lookup_n(toks, owners, jnp.asarray(hs), n, n_servers))
            for row, h in zip(got, hs):
                want = [servers.index(s) for s in r._lookup_n_hash(int(h), n)]
                want += [-1] * (n - len(want))
                assert row.tolist() == want, (h, n, row.tolist(), want)


def test_graft_entry_points():
    import __graft_entry__ as g

    fn, args = g.entry()
    out = jax.jit(fn)(*args)
    jax.block_until_ready(out)
    assert int(out.tick) == 1
    g.dryrun_multichip(8)
