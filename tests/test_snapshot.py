"""Checkpoint/resume: sim pytree snapshots + host-plane membership export
(a capability the reference lacks by design — SURVEY §5 checkpoint/resume)."""

import asyncio
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ringpop_tpu.sim import delta, fullview, lifecycle
from ringpop_tpu.sim.snapshot import (
    export_membership,
    import_membership,
    load_state,
    save_state,
)


def _trees_equal(a, b):
    return all(
        np.array_equal(np.asarray(x), np.asarray(y)) for x, y in zip(a, b)
    )


@pytest.mark.parametrize(
    "mk",
    [
        lambda: (delta, delta.DeltaParams(n=64, k=8), delta.init_state(delta.DeltaParams(n=64, k=8), seed=3), delta.DeltaState),
        lambda: (fullview, fullview.FullViewParams(n=16), fullview.init_state(fullview.FullViewParams(n=16), seed=3), fullview.FullViewState),
        lambda: (lifecycle, lifecycle.LifecycleParams(n=32, k=8), lifecycle.init_state(lifecycle.LifecycleParams(n=32, k=8), seed=3), lifecycle.LifecycleState),
    ],
    ids=["delta", "fullview", "lifecycle"],
)
def test_roundtrip_and_resume_bitexact(tmp_path, mk):
    """Snapshot mid-run; the resumed trajectory must equal the original."""
    eng, params, state, cls = mk()
    for _ in range(5):
        state = eng.step(params, state)
    path = str(tmp_path / "snap.npz")
    save_state(path, state)

    # continue original 5 more ticks
    cont = state
    for _ in range(5):
        cont = eng.step(params, cont)

    # resume from disk 5 ticks — bit-identical (PRNG key included)
    resumed = load_state(path, cls)
    assert _trees_equal(resumed, state)
    for _ in range(5):
        resumed = eng.step(params, resumed)
    assert _trees_equal(resumed, cont)


def test_orbax_async_roundtrip_bitexact(tmp_path):
    """The orbax backend must round-trip bit-exactly (PRNG key included)
    while the sim keeps stepping DURING the async save — the non-blocking
    property is the point of the backend."""
    pytest.importorskip("orbax.checkpoint")
    from ringpop_tpu.sim.snapshot import load_state_orbax, save_state_orbax

    params = lifecycle.LifecycleParams(n=48, k=8)
    state = lifecycle.init_state(params, seed=9)
    for _ in range(5):
        state = lifecycle.step(params, state)
    snap = state  # jax arrays are immutable — the saved value can't change

    path = str(tmp_path / "orbax_ckpt")
    ckptr = save_state_orbax(path, state)
    # keep stepping while the write is in flight
    cont = state
    for _ in range(5):
        cont = lifecycle.step(params, cont)
    ckptr.wait_until_finished()
    ckptr.close()

    example = lifecycle.init_state(params, seed=0)
    resumed = load_state_orbax(path, example)
    assert _trees_equal(resumed, snap)
    for _ in range(5):
        resumed = lifecycle.step(params, resumed)
    assert _trees_equal(resumed, cont)


def test_orbax_shape_mismatch_raises(tmp_path):
    pytest.importorskip("orbax.checkpoint")
    from ringpop_tpu.sim.snapshot import load_state_orbax, save_state_orbax

    params = lifecycle.LifecycleParams(n=48, k=8)
    state = lifecycle.init_state(params, seed=9)
    path = str(tmp_path / "orbax_ckpt")
    save_state_orbax(path, state, wait=True)
    wrong = lifecycle.init_state(lifecycle.LifecycleParams(n=32, k=8), seed=0)
    with pytest.raises(ValueError, match="wrong engine config"):
        load_state_orbax(path, wrong)


def test_type_and_field_validation(tmp_path):
    params = delta.DeltaParams(n=16, k=4)
    state = delta.init_state(params, seed=0)
    path = str(tmp_path / "snap.npz")
    save_state(path, state)
    with pytest.raises(ValueError, match="snapshot holds DeltaState"):
        load_state(path, lifecycle.LifecycleState)
    with pytest.raises(ValueError, match="not a ringpop_tpu snapshot"):
        np.savez(str(tmp_path / "bogus.npz"), a=np.zeros(3))
        load_state(str(tmp_path / "bogus.npz"), delta.DeltaState)


def test_host_membership_export_import(tmp_path):
    from tests.swim_utils import bootstrap_nodes, make_nodes, make_node
    from ringpop_tpu.net import LocalNetwork

    async def run():
        network = LocalNetwork()
        nodes = make_nodes(3, network)
        await bootstrap_nodes(nodes)

        path = str(tmp_path / "membership.json")
        changes = export_membership(nodes[0].memberlist, path)
        assert len(changes) == 3
        # wire schema fields (member.go JSON tags)
        assert {"address", "status", "incarnationNumber", "source"} <= set(changes[0])

        # warm boot: a fresh node applies the snapshot before gossiping
        fresh = make_node(network, "127.0.0.1:3100", seed=7)
        fresh.memberlist.reincarnate()
        n_applied = import_membership(fresh.memberlist, path)
        assert n_applied == 3
        addrs = {m.address for m in fresh.memberlist.get_members()}
        assert {n.address for n in nodes} <= addrs

        # stale snapshots are harmless: re-import applies nothing new
        assert import_membership(fresh.memberlist, changes) == 0
        for n in nodes:
            n.destroy()

    asyncio.run(run())


@pytest.mark.parametrize(
    "engine,k", [("delta", 8), ("lifecycle", 8), ("lifecycle", 40)]
)
def test_pre_ride_ok_snapshot_migrates(tmp_path, engine, k):
    """Snapshots written before the packed engines stored ``learned`` as an
    UNPACKED bool[N, K] plane and carried no ride_ok; load_state must pack
    the plane and reconstruct the gate instead of refusing — old
    long-running-sim checkpoints stay loadable.  k=8 (one word) covers the
    silent-broadcast hazard, k=40 (two words) the shape-error one."""
    import json

    from ringpop_tpu.sim.packbits import unpack_bits

    if engine == "delta":
        params = delta.DeltaParams(n=48, k=k)
        state = delta.init_state(params, seed=5)
        cls, eng_step = delta.DeltaState, delta.step
        faults = ()
    else:
        params = lifecycle.LifecycleParams(n=48, k=k, suspect_ticks=4)
        faults = (delta.DeltaFaults(up=jnp.ones(48, bool).at[3].set(False)),)
        state = lifecycle.init_state(params, seed=5)
        cls, eng_step = lifecycle.LifecycleState, lifecycle.step
    for _ in range(6):
        state = eng_step(params, state, *faults)

    # forge the TRUE old on-disk schema: learned as bool[N, K] (unpacked),
    # no ride_ok field, meta without it
    path = str(tmp_path / "old.npz")
    save_state(path, state)
    with np.load(path) as data:
        arrays = {f: data[f] for f in data.files if f not in ("__meta__", "ride_ok")}
    arrays["learned"] = np.asarray(unpack_bits(state.learned, params.k))
    assert arrays["learned"].dtype == bool and arrays["learned"].shape == (48, k)
    meta = json.dumps(
        {
            "magic": "ringpop_tpu-snapshot-v1",
            "type": cls.__name__,
            "fields": [f for f in cls._fields if f != "ride_ok"],
        }
    )
    np.savez_compressed(
        path, __meta__=np.frombuffer(meta.encode(), dtype=np.uint8), **arrays
    )

    restored = load_state(path, cls, params=params)
    assert _trees_equal(restored, state)  # learned re-packed, ride_ok rebuilt
    # and the loaded state must STEP identically to the packed original
    cont, rcont = state, restored
    for _ in range(4):
        cont = eng_step(params, cont, *faults)
        rcont = eng_step(params, rcont, *faults)
    assert _trees_equal(rcont, cont)
    # without params the default SWIM bound is assumed — loudly (these
    # configs use the default p_factor/max_p, so the result still matches)
    with pytest.warns(UserWarning, match="assuming the default dissemination"):
        restored_default = load_state(path, cls)
    assert _trees_equal(restored_default, state)


def test_snapshot_meta_max_p_rides_migration(tmp_path):
    """A snapshot saved with params persists the resolved max_p in its
    meta; a migration that must rebuild ride_ok without a params argument
    uses it (no warning, correct gate) even for a custom bound."""
    import json

    from ringpop_tpu.sim.packbits import pack_bool, unpack_bits

    params = delta.DeltaParams(n=48, k=8, max_p=3)  # custom, non-default bound
    state = delta.init_state(params, seed=5)
    for _ in range(6):
        state = delta.step(params, state)

    path = str(tmp_path / "old.npz")
    save_state(path, state, params=params)
    with np.load(path) as data:
        meta = json.loads(bytes(data["__meta__"]).decode())
        arrays = {f: data[f] for f in data.files if f not in ("__meta__", "ride_ok")}
    assert meta["max_p"] == 3
    arrays["learned"] = np.asarray(unpack_bits(state.learned, params.k))
    meta["fields"] = [f for f in delta.DeltaState._fields if f != "ride_ok"]
    np.savez_compressed(
        path,
        __meta__=np.frombuffer(json.dumps(meta).encode(), dtype=np.uint8),
        **arrays,
    )

    import warnings

    with warnings.catch_warnings():
        warnings.simplefilter("error")  # meta max_p must suppress the warning
        restored = load_state(path, delta.DeltaState)
    assert _trees_equal(restored, state)
    assert np.array_equal(
        np.asarray(restored.ride_ok), np.asarray(pack_bool(state.pcount < np.int8(3)))
    )


@pytest.mark.slow
def test_headline_scale_snapshot_roundtrip_and_resume(tmp_path):
    """Checkpoint/resume at the HEADLINE scale (1M x 256): the small-n
    tests prove the mechanics; this proves the flagship shape survives a
    save/load bit-exactly and that a resumed run steps identically to the
    uninterrupted one — the at-scale analog of the reference's restart
    path.  Also pins the cost class: the packed planes compress a 1M-node
    mid-dissemination state to ~MBs, seconds to write on one core."""
    n, k = 1_000_000, 256
    params = lifecycle.LifecycleParams(n=n, k=k)
    rng = np.random.default_rng(0)
    victims = np.sort(rng.choice(n, 1000, replace=False))
    up = np.ones(n, bool)
    up[victims] = False
    faults = delta.DeltaFaults(up=jnp.asarray(up))

    state = lifecycle.init_state(params, seed=0)
    for _ in range(3):  # real in-flight rumors, not a blank state
        state = lifecycle.step(params, state, faults)
    jax.block_until_ready(state.learned)

    path = str(tmp_path / "snap1m.npz")
    save_state(path, state, params=params)
    loaded = load_state(path, lifecycle.LifecycleState, params=params)
    assert _trees_equal(loaded, state)
    # the advertised cost class: packed planes keep the on-disk state
    # orders of magnitude under the raw 290 MB of its dense planes
    assert os.path.getsize(path) < 64 * 2**20

    s_cont, s_res = state, loaded
    for _ in range(2):
        s_cont = lifecycle.step(params, s_cont, faults)
        s_res = lifecycle.step(params, s_res, faults)
    assert _trees_equal(s_cont, s_res)
