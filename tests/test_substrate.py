"""Substrate tests: util helpers, mock clock deadline wheel, event bus,
discovery providers, logging facility."""

import json
import logging as stdlog
import random

import pytest

from ringpop_tpu import util
from ringpop_tpu import logging as rlog
from ringpop_tpu.discovery import JSONFile, StaticHosts, as_provider
from ringpop_tpu.events import EventEmitter, on
from ringpop_tpu.util.clock import Clock, MockClock


class TestUtil:
    def test_capture_host(self):
        assert util.capture_host("10.0.0.1:3000") == "10.0.0.1"
        assert util.capture_host("nonsense") == ""

    def test_host_ports_by_host(self):
        got = util.host_ports_by_host(["a:1", "a:2", "b:1"])
        assert got == {"a": ["a:1", "a:2"], "b": ["b:1"]}

    def test_hostname_ip_mismatch(self):
        assert util.check_hostname_ip_mismatch("10.0.0.1:1", ["10.0.0.2:1"]) is None
        assert util.check_hostname_ip_mismatch("10.0.0.1:1", ["host:1"]) is not None

    def test_single_node_cluster(self):
        assert util.single_node_cluster("a:1", ["a:1"])
        assert not util.single_node_cluster("a:1", ["a:1", "b:2"])

    def test_select_zero_means_default(self):
        assert util.select_int(0, 7) == 7
        assert util.select_int(3, 7) == 3
        assert util.select_duration(0.0, 1.5) == 1.5

    def test_take_node(self):
        nodes = ["a", "b", "c"]
        got = util.take_node(nodes, 1)
        assert got == "b" and nodes == ["a", "c"]
        rng = random.Random(0)
        while nodes:
            assert util.take_node(nodes, -1, rng) is not None
        assert util.take_node(nodes) is None

    def test_shuffle_is_permutation(self):
        xs = [str(i) for i in range(20)]
        got = util.shuffle_strings(xs, random.Random(1))
        assert sorted(got) == sorted(xs) and got != xs


class TestClock:
    def test_mock_clock_fires_in_order(self):
        c = MockClock()
        fired = []
        c.after(2.0, lambda: fired.append("b"))
        c.after(1.0, lambda: fired.append("a"))
        c.after(9.0, lambda: fired.append("z"))
        c.advance(2.5)
        assert fired == ["a", "b"]
        c.advance(10)
        assert fired == ["a", "b", "z"]

    def test_cancel(self):
        c = MockClock()
        fired = []
        t = c.after(1.0, lambda: fired.append(1))
        t.stop()
        c.advance(2.0)
        assert fired == []

    def test_timer_scheduled_by_timer_fires_same_advance(self):
        c = MockClock()
        fired = []
        c.after(1.0, lambda: c.after(1.0, lambda: fired.append("inner")))
        c.advance(3.0)
        assert fired == ["inner"]

    def test_now_ms(self):
        c = MockClock(start=12.5)
        assert c.now_ms() == 12500


class TestEvents:
    def test_emit_and_filter(self):
        bus = EventEmitter()
        got = []
        on(bus, str, got.append)
        bus.emit("hello")
        bus.emit(42)  # filtered out
        assert got == ["hello"]

    def test_deregister(self):
        bus = EventEmitter()
        got = []
        l = on(bus, str, got.append)
        bus.deregister_listener(l)
        bus.emit("x")
        assert got == []


class TestDiscovery:
    def test_static(self):
        p = StaticHosts("a:1", "b:2")
        assert p.hosts() == ["a:1", "b:2"]

    def test_jsonfile(self, tmp_path):
        f = tmp_path / "hosts.json"
        f.write_text(json.dumps(["a:1", "b:2"]))
        assert JSONFile(str(f)).hosts() == ["a:1", "b:2"]

    def test_jsonfile_rejects_non_list(self, tmp_path):
        f = tmp_path / "bad.json"
        f.write_text('{"not": "a list"}')
        with pytest.raises(ValueError):
            JSONFile(str(f)).hosts()

    def test_as_provider_coercions(self, tmp_path):
        assert as_provider(["a:1"]).hosts() == ["a:1"]
        assert as_provider(lambda: ["b:2"]).hosts() == ["b:2"]
        f = tmp_path / "h.json"
        f.write_text('["c:3"]')
        assert as_provider(str(f)).hosts() == ["c:3"]


class TestLogging:
    def test_named_levels(self, caplog):
        fac = rlog.Facility(stdlog.getLogger("test-ringpop"))
        lg = fac.logger("gossip")
        with caplog.at_level(stdlog.DEBUG, logger="test-ringpop"):
            lg.info("dropped")  # default min level is error
            fac.set_level("gossip", "info")
            lg.info("kept")
        assert "kept" in caplog.text and "dropped" not in caplog.text

    def test_with_fields(self):
        lg = rlog.logger("x").with_field("local", "a:1").with_fields(k=2)
        assert lg._fields == {"local": "a:1", "k": 2}

    def test_parse_level(self):
        assert rlog.parse_level("warn") == stdlog.WARNING
        with pytest.raises(ValueError):
            rlog.parse_level("nope")
