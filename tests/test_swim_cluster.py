"""Tier-2 functional tests: multi-node in-process clusters driven
synchronously (model: reference swim package tests + test_utils.go)."""

import asyncio

import pytest

from ringpop_tpu.net import CallError, LocalNetwork
from ringpop_tpu.swim.member import ALIVE, FAULTY, LEAVE, SUSPECT, TOMBSTONE
from ringpop_tpu.swim.join import send_join_request

from swim_utils import (
    bootstrap_nodes,
    converged,
    make_node,
    make_nodes,
    member_statuses,
    run,
    tick_all,
    wait_for_convergence,
)


def test_two_node_bootstrap_converges():
    async def main():
        nodes = make_nodes(2)
        await bootstrap_nodes(nodes)
        await wait_for_convergence(nodes)
        for n in nodes:
            assert n.member_count() == 2
            assert all(s == ALIVE for s in member_statuses(n).values())
        assert nodes[0].memberlist.checksum() == nodes[1].memberlist.checksum()

    run(main())


def test_five_node_cluster_converges():
    async def main():
        nodes = make_nodes(5)
        await bootstrap_nodes(nodes)
        await wait_for_convergence(nodes)
        for n in nodes:
            assert n.member_count() == 5
            assert n.count_reachable_members() == 5

    run(main())


def test_single_node_cluster_shortcut():
    async def main():
        nodes = make_nodes(1)
        await bootstrap_nodes(nodes)
        assert nodes[0].ready()
        assert nodes[0].member_count() == 1

    run(main())


def test_suspect_declaration_disseminates():
    async def main():
        nodes = make_nodes(4)
        await bootstrap_nodes(nodes)
        await wait_for_convergence(nodes)

        victim = nodes[3]
        declarer = nodes[0]
        member = declarer.memberlist.member(victim.address)
        # black-hole the victim so it cannot refute
        nodes[0].channel.network.black_hole(victim.address)
        declarer.memberlist.make_suspect(victim.address, member.incarnation)
        assert member_statuses(declarer)[victim.address] == SUSPECT

        others = nodes[:3]
        for _ in range(30):
            await tick_all(others)
            if all(member_statuses(n)[victim.address] == SUSPECT for n in others):
                break
        for n in others:
            assert member_statuses(n)[victim.address] == SUSPECT

    run(main())


def test_refutation_on_suspect():
    async def main():
        nodes = make_nodes(3)
        await bootstrap_nodes(nodes)
        await wait_for_convergence(nodes)

        victim = nodes[2]
        old_inc = victim.incarnation()
        member = nodes[0].memberlist.member(victim.address)
        nodes[0].memberlist.make_suspect(victim.address, member.incarnation)

        # gossip until the victim hears the rumor and refutes
        for _ in range(30):
            await tick_all(nodes)
            if victim.incarnation() > old_inc:
                break
        assert victim.incarnation() > old_inc
        assert member_statuses(victim)[victim.address] == ALIVE

        await wait_for_convergence(nodes)
        for n in nodes:
            assert member_statuses(n)[victim.address] == ALIVE

    run(main())


def test_failure_detection_black_hole_to_suspect():
    async def main():
        network = LocalNetwork()
        nodes = make_nodes(4, network)
        await bootstrap_nodes(nodes)
        await wait_for_convergence(nodes)

        victim = nodes[3]
        network.black_hole(victim.address)
        alive = nodes[:3]

        # pings + ping-reqs fail -> suspect
        for _ in range(40):
            await tick_all(alive)
            if all(member_statuses(n)[victim.address] == SUSPECT for n in alive):
                break
        for n in alive:
            assert member_statuses(n)[victim.address] == SUSPECT

        # suspect period (5s) passes -> faulty
        for n in alive:
            n.clock.advance(6.0)
        for n in alive:
            assert member_statuses(n)[victim.address] == FAULTY

    run(main())


def test_faulty_node_rejoins_and_recovers():
    async def main():
        network = LocalNetwork()
        nodes = make_nodes(3, network)
        await bootstrap_nodes(nodes)
        await wait_for_convergence(nodes)

        victim = nodes[2]
        network.black_hole(victim.address)
        alive = nodes[:2]
        for _ in range(40):
            await tick_all(alive)
            if all(member_statuses(n)[victim.address] == SUSPECT for n in alive):
                break
        for n in alive:
            n.clock.advance(6.0)
        assert member_statuses(alive[0])[victim.address] == FAULTY

        # network comes back; victim reasserts itself by gossiping
        network.unblack_hole(victim.address)
        victim.memberlist.reincarnate()
        for _ in range(60):
            await tick_all(nodes)
            if converged(nodes) and all(
                member_statuses(n)[victim.address] == ALIVE for n in nodes
            ):
                break
        for n in nodes:
            assert member_statuses(n)[victim.address] == ALIVE

    run(main())


def test_join_rejects_self_and_wrong_app():
    async def main():
        network = LocalNetwork()
        a = make_node(network, "127.0.0.1:3000", app="appA")
        b = make_node(network, "127.0.0.1:3001", app="appB")
        await bootstrap_nodes([a], stop_gossip=True)
        await bootstrap_nodes([b], stop_gossip=True)

        with pytest.raises(CallError, match="app"):
            await send_join_request(b, a.address, 0.5)

        # self-join rejected server-side
        with pytest.raises(CallError, match="itself"):
            body = {
                "app": "appA",
                "source": a.address,
                "incarnationNumber": 1,
                "timeout": 0.5,
            }
            await a.channel.call(a.address, "ringpop", "/protocol/join", body, timeout=0.5)

    run(main())


def test_full_sync_repairs_divergence():
    async def main():
        nodes = make_nodes(2)
        await bootstrap_nodes(nodes)
        await wait_for_convergence(nodes)

        # create divergence by fiat: apply a member only on node 0 and clear
        # its dissemination so only a checksum mismatch remains
        from ringpop_tpu.swim.member import Change

        ghost = Change(address="127.0.0.1:9999", incarnation=1, status=ALIVE, source="fiat")
        nodes[0].memberlist.update([ghost])
        nodes[0].disseminator.clear_changes()
        assert nodes[0].memberlist.checksum() != nodes[1].memberlist.checksum()

        # a ping from 0 to 1 carries no changes but mismatched checksum ->
        # node 1 answers with a full sync
        await wait_for_convergence(nodes)
        assert nodes[1].memberlist.member("127.0.0.1:9999") is not None

    run(main())


def test_state_transition_chain_to_eviction():
    async def main():
        network = LocalNetwork()
        nodes = make_nodes(3, network)
        await bootstrap_nodes(nodes)
        await wait_for_convergence(nodes)

        victim = nodes[2]
        network.black_hole(victim.address)
        watcher = nodes[0]
        m = watcher.memberlist.member(victim.address)
        watcher.memberlist.make_suspect(victim.address, m.incarnation)

        watcher.clock.advance(6.0)  # suspect(5s) -> faulty
        assert member_statuses(watcher)[victim.address] == FAULTY
        watcher.clock.advance(24 * 3600 + 1)  # faulty(24h) -> tombstone
        assert member_statuses(watcher)[victim.address] == TOMBSTONE
        watcher.clock.advance(61)  # tombstone(60s) -> evicted
        assert watcher.memberlist.member(victim.address) is None

    run(main())


def test_admin_handlers():
    async def main():
        nodes = make_nodes(2)
        await bootstrap_nodes(nodes)
        await wait_for_convergence(nodes)
        a, b = nodes

        # /admin/tick drives one protocol period remotely
        res = await a.channel.call(b.address, "ringpop", "/admin/tick", {}, timeout=1.0)
        assert res["checksum"] == b.memberlist.checksum()

        # /admin/member/leave declares leave; node stays in the member table
        res = await a.channel.call(b.address, "ringpop", "/admin/member/leave", {}, timeout=1.0)
        assert res["status"] == "ok"
        assert member_statuses(b)[b.address] == LEAVE

        # /admin/member/join reincarnates (advance time so the new wall-ms
        # incarnation strictly exceeds the one the leave was declared at)
        b.clock.advance(0.1)
        res = await a.channel.call(b.address, "ringpop", "/admin/member/join", {}, timeout=1.0)
        assert res["status"] == "rejoined"
        assert member_statuses(b)[b.address] == ALIVE

        # reap: faulty -> tombstone
        m = b.memberlist.member(a.address)
        b.memberlist.make_faulty(a.address, m.incarnation)
        await a.channel.call(b.address, "ringpop", "/admin/reap", {}, timeout=1.0)
        assert member_statuses(b)[a.address] == TOMBSTONE

    run(main())


def test_leave_rejoin_cycle_via_gossip():
    async def main():
        nodes = make_nodes(3)
        await bootstrap_nodes(nodes)
        await wait_for_convergence(nodes)

        leaver = nodes[2]
        leaver.memberlist.make_leave(leaver.address, leaver.incarnation())
        await wait_for_convergence(nodes)
        for n in nodes[:2]:
            assert member_statuses(n)[leaver.address] == LEAVE

        leaver.memberlist.reincarnate()
        await wait_for_convergence(nodes)
        for n in nodes:
            assert member_statuses(n)[leaver.address] == ALIVE

    run(main())


def test_packet_loss_still_converges():
    async def main():
        network = LocalNetwork(seed=7)
        network.drop_rate = 0.05  # BASELINE config: 5% loss scenario
        nodes = make_nodes(5, network)
        await bootstrap_nodes(nodes)
        await wait_for_convergence(nodes, max_ticks=400)
        for n in nodes:
            assert n.count_reachable_members() == 5

    run(main())


def test_first_seen_tombstone_is_refused():
    # an evicted tombstone arriving via full sync must not be re-imported
    # (parity: memberlist.go:421-426)
    async def main():
        nodes = make_nodes(2)
        await bootstrap_nodes(nodes)
        await wait_for_convergence(nodes)
        from ringpop_tpu.swim.member import Change, TOMBSTONE as TS

        ghost = Change(address="127.0.0.1:9998", incarnation=1, status=TS, source="fiat")
        applied = nodes[0].memberlist.update([ghost])
        assert applied == []
        assert nodes[0].memberlist.member("127.0.0.1:9998") is None

    run(main())
