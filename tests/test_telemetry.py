"""Telemetry-plane acceptance tests (sim/telemetry.py).

The two load-bearing properties:

1. **Bit-identity** — a telemetry-on run produces exactly the
   telemetry-off state, tick for tick, across exchange modes, fault
   models, heal on/off, and the run_until drivers (telemetry reads
   intermediates; it must never feed back or consume PRNG draws).
2. **Fidelity** — the fetched counters mean what they claim: paired
   against brute-force recomputation from the per-tick states, and
   against conservation laws (an all-up lossless cluster pings N times a
   tick and declares nothing).

Plus the plumbing: fetch-resets, journal records/headers, the stats/event
bridges, the state digest, the DeltaSim journal hook, and the
golden-drift diagnosis helper.
"""

from __future__ import annotations

import functools
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ringpop_tpu.events import EventEmitter, SimTickBlockEvent, on
from ringpop_tpu.options import InMemoryStats
from ringpop_tpu.sim import lifecycle, telemetry
from ringpop_tpu.sim.delta import DeltaFaults, DeltaSim

from tests import golden_tools


def _leaves_equal(a, b) -> bool:
    return all(
        bool((np.asarray(x) == np.asarray(y)).all())
        for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b))
    )


def _faults(n, n_victims=3, drop=0.0, seed=0):
    rng = np.random.default_rng(seed)
    victims = np.sort(rng.choice(n, size=n_victims, replace=False))
    up = np.ones(n, bool)
    up[victims] = False
    return victims, DeltaFaults(up=jnp.asarray(up), drop_rate=drop)


@pytest.mark.parametrize(
    "pkw,drop",
    [
        (dict(k=32, suspect_ticks=6), 0.0),
        (dict(k=32, suspect_ticks=6, exchange="uniform"), 0.05),
        (dict(k=32, suspect_ticks=6, heal_prob=0.0), 0.05),
    ],
    ids=["shift", "uniform_drop", "no_heal"],
)
def test_step_bit_identical_with_telemetry(pkw, drop):
    n, ticks = 96, 40
    params = lifecycle.LifecycleParams(n=n, **pkw)
    _, faults = _faults(n, drop=drop)
    s_off = lifecycle.init_state(params, seed=5)
    s_on = lifecycle.init_state(params, seed=5)
    tel = telemetry.zeros(params)
    stepper = jax.jit(functools.partial(lifecycle.step, params))
    for _ in range(ticks):
        s_off = stepper(s_off, faults)
        s_on, tel = stepper(s_on, faults, telemetry=tel)
    assert _leaves_equal(s_off, s_on)
    assert int(tel.ticks) == ticks


def test_counters_match_bruteforce_recomputation():
    """Fetched counters equal sums recomputed from the per-tick state
    evolution: ping_send from the fault-free shift topology, declarations
    from the rumor table's placement history."""
    n, ticks = 64, 50
    params = lifecycle.LifecycleParams(n=n, k=32, suspect_ticks=8)
    victims, faults = _faults(n, n_victims=2)
    sim = lifecycle.LifecycleSim(n=n, k=32, seed=1, suspect_ticks=8, telemetry=True)
    live = int(np.asarray(faults.up).sum())
    for _ in range(ticks):
        sim.tick(faults)
    rec = sim.fetch_telemetry(faults)
    assert rec["ticks"] == ticks
    # shift topology, no drops: every live node whose belief allows the
    # probe pings once a tick; dead targets/probers account for the gap
    assert rec["ping_send"] <= live * ticks
    assert rec["ping_send"] >= (live - 2 * len(victims)) * ticks
    # victims were declared: suspect placements >= victims, faulty followed
    assert rec["decl_suspect"] >= len(victims)
    assert rec["decl_faulty"] >= len(victims)
    assert rec["timer_fired"] >= len(victims)
    assert rec["ping_timeout"] > 0 and rec["ping_req_send"] > 0
    assert rec["rumors_piggybacked"] > 0
    assert rec["detect_frac"] == pytest.approx(1.0)
    assert rec["census_faulty"] == len(victims)
    assert rec["num_members"] == n
    # fetch reset the accumulators
    rec2 = sim.fetch_telemetry(faults)
    assert rec2["ticks"] == 0 and rec2["ping_send"] == 0
    # census is point-in-time, not accumulated — it survives the reset
    assert rec2["census_faulty"] == len(victims)


def test_quiet_cluster_conserves():
    """All nodes up, no loss, no victims: exactly N pings per tick, no
    failed probes, no declarations, no timers, detect_frac saturated."""
    n, ticks = 48, 30
    sim = lifecycle.LifecycleSim(n=n, k=16, seed=2, telemetry=True)
    for _ in range(ticks):
        sim.tick()
    rec = sim.fetch_telemetry()
    assert rec["ping_send"] == n * ticks
    for key in ("ping_timeout", "ping_req_send", "decl_suspect", "decl_faulty",
                "decl_tombstone", "decl_alive", "refuted", "timer_fired"):
        assert rec[key] == 0, key
    assert rec["census_alive"] == n
    assert rec["detect_frac"] == pytest.approx(1.0)


def test_run_until_detected_bit_identical_and_flushes():
    n = 128
    victims, faults = _faults(n, n_victims=4, seed=3)
    sink = telemetry.TelemetrySink()
    sim = lifecycle.LifecycleSim(n=n, k=64, seed=3, suspect_ticks=10, telemetry=sink)
    ticks, ok = sim.run_until_detected(victims, faults, max_ticks=1024)
    ref = lifecycle.LifecycleSim(n=n, k=64, seed=3, suspect_ticks=10)
    rticks, rok = ref.run_until_detected(victims, faults, max_ticks=1024)
    assert (ticks, ok) == (rticks, rok) and ok
    assert _leaves_equal(sim.state, ref.state)
    # one flushed record per dispatch, counters covering every tick run
    assert sink.records
    assert sum(r["ticks"] for r in sink.records) == ticks
    assert all("state_digest" in r for r in sink.records)
    # quiescence driver flushes too, and states stay paired
    sim.run_until_converged(faults, max_ticks=1024)
    ref.run_until_converged(faults, max_ticks=1024)
    assert _leaves_equal(sim.state, ref.state)


def test_block_accumulation_equals_per_tick_stepping():
    """_run_block's carried accumulator equals per-tick accumulation —
    the fori carry loses nothing."""
    n, ticks = 64, 24
    params = lifecycle.LifecycleParams(n=n, k=32, suspect_ticks=6)
    _, faults = _faults(n, seed=4)
    blk = jax.jit(
        functools.partial(lifecycle._run_block, params), static_argnames="ticks"
    )
    s_blk, t_blk = blk(
        lifecycle.init_state(params, seed=7), faults, ticks=ticks,
        telemetry=telemetry.zeros(params),
    )
    stepper = jax.jit(functools.partial(lifecycle.step, params))
    s_tick = lifecycle.init_state(params, seed=7)
    t_tick = telemetry.zeros(params)
    for _ in range(ticks):
        s_tick, t_tick = stepper(s_tick, faults, telemetry=t_tick)
    assert _leaves_equal(s_blk, s_tick)
    assert _leaves_equal(t_blk, t_tick)


def test_sink_fans_out_journal_stats_events(tmp_path):
    path = str(tmp_path / "run.jsonl")
    stats = InMemoryStats()
    emitter = EventEmitter()
    got_events = []
    on(emitter, SimTickBlockEvent, got_events.append)
    n = 96
    victims, faults = _faults(n, seed=5)
    with telemetry.TelemetryJournal(path) as journal:
        journal.header("lifecycle", "unit", {"n": n})
        sink = telemetry.TelemetrySink(journal=journal, stats=stats, emitter=emitter)
        sim = lifecycle.LifecycleSim(
            n=n, k=32, seed=5, suspect_ticks=8, telemetry=sink, journal_views=True
        )
        sim.run(32, faults)
    records = telemetry.read_journal(path)
    assert records[0]["kind"] == "header"
    assert records[0]["toolchain"]["jax"] == jax.__version__
    assert "mesh_budget" in records[0]
    blocks = [r for r in records if r["kind"] == "block"]
    assert blocks and blocks[0]["ticks"] == 32
    # journal_views: the view-checksum summary rode along
    assert "views_sum" in blocks[0] and "views_agree" in blocks[0]
    # every journal value is a plain JSON scalar
    assert all(
        isinstance(v, (int, float, str, bool, dict, type(None)))
        for r in records for v in r.values()
    )
    # stats bridge: host-plane namespace under ringpop.sim
    assert stats.counters.get("ringpop.sim.ping.send", 0) > 0
    assert "ringpop.sim.num-members" in stats.gauges
    # event bridge
    assert len(got_events) == len(blocks)
    assert got_events[0].record["ticks"] == 32


def test_tree_digest_detects_single_bit_flip():
    params = lifecycle.LifecycleParams(n=32, k=32)
    s = lifecycle.init_state(params, seed=0)
    d1 = telemetry.tree_digest(s)
    d2 = telemetry.tree_digest(lifecycle.init_state(params, seed=0))
    assert int(d1) == int(d2)
    flipped = s._replace(learned=s.learned.at[3, 0].set(s.learned[3, 0] ^ 1))
    assert int(telemetry.tree_digest(flipped)) != int(d1)
    # and it is order/position sensitive (swapping two rows changes it)
    swapped = s._replace(self_inc=s.self_inc.at[0].set(1))
    assert int(telemetry.tree_digest(swapped)) != int(d1)


def test_delta_sim_journal_hook_bit_identical():
    rows = []
    d = DeltaSim(n=256, k=32, seed=9, telemetry_sink=lambda r: rows.append(jax.device_get(r)))
    ticks, ok = d.run_until_converged(max_ticks=512, journal_every=16)
    ref = DeltaSim(n=256, k=32, seed=9)
    rticks, rok = ref.run_until_converged(max_ticks=512)
    assert ok and rok and ticks == rticks
    assert _leaves_equal(d.state, ref.state)
    assert rows and float(rows[-1]["coverage"]) == pytest.approx(1.0)
    assert [int(r["tick"]) for r in rows] == sorted(int(r["tick"]) for r in rows)
    assert int(rows[-1]["digest"]) == int(telemetry.tree_digest(ref.state))


def test_montecarlo_unaffected_by_telemetry_seam():
    """The vmapped Monte-Carlo engine goes through the telemetry=None
    default — replica 0 must still be bit-identical to a solo sim."""
    from ringpop_tpu.sim.montecarlo import MonteCarlo

    n = 64
    mc = MonteCarlo(lifecycle.LifecycleParams(n=n, k=16), seeds=[11, 12])
    mc.run(8)
    solo = lifecycle.LifecycleSim(n=n, k=16, seed=11)
    solo.run(8)
    rep0 = jax.tree.map(lambda x: np.asarray(x)[0], mc.states)
    assert _leaves_equal(rep0, solo.state)


# -- golden drift diagnosis (tests/golden_tools.py) --------------------------


class _FakeNpz(dict):
    @property
    def files(self):
        return list(self.keys())


def test_golden_fingerprint_roundtrip_and_diagnosis():
    out = {}
    golden_tools.embed(out)
    npz = _FakeNpz(out)
    assert golden_tools.recorded(npz) == golden_tools.fingerprint()

    # same-toolchain mismatch → real regression
    with pytest.raises(pytest.fail.Exception) as e:
        golden_tools.fail_golden(npz, "cfg", "learned", 3)
    assert "REAL REGRESSION" in str(e.value)

    # different-toolchain mismatch → drift
    stale = dict(golden_tools.fingerprint(), jax="0.0.1")
    npz_drift = _FakeNpz({golden_tools.TOOLCHAIN_KEY: np.array(json.dumps(stale))})
    with pytest.raises(pytest.fail.Exception) as e:
        golden_tools.fail_golden(npz_drift, "cfg", "learned", 3)
    assert "TOOLCHAIN DRIFT" in str(e.value)

    # unrecorded (the committed pre-fingerprint goldens) → drift suspected
    with pytest.raises(pytest.fail.Exception) as e:
        golden_tools.fail_golden(_FakeNpz({}), "cfg", "learned", 0)
    assert "UNRECORDED" in str(e.value)


# -- CLI reporters: close()/context-manager (satellite) ----------------------


def test_file_stats_context_manager_flushes_and_closes(tmp_path):
    from ringpop_tpu.cli.stats import FileStats

    path = str(tmp_path / "stats.out")
    with FileStats(path) as fs:
        fs.incr("a.counter", 2)
        fs.gauge("a.gauge", 1.5)
        fs.timing("a.timing", 0.25)
        handle = fs._f
    assert handle.closed
    lines = open(path).read().strip().split("\n")
    assert len(lines) == 3 and "count a.counter 2" in lines[0]
    fs.close()  # idempotent
    fs.incr("late", 1)  # post-close emits are dropped, not raised
    assert len(open(path).read().strip().split("\n")) == 3


def test_udp_statsd_context_manager_closes_socket(tmp_path):
    import socket

    from ringpop_tpu.cli.stats import UDPStatsd

    recv = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    recv.bind(("127.0.0.1", 0))
    recv.settimeout(2.0)
    port = recv.getsockname()[1]
    with UDPStatsd(f"127.0.0.1:{port}") as udp:
        udp.incr("x", 3)
        sock = udp._sock
    assert udp._sock is None and sock.fileno() == -1
    udp.close()  # idempotent
    udp.gauge("late", 1.0)  # dropped silently after close
    assert recv.recv(64) == b"x:3|c"
    recv.close()


def test_udp_statsd_sends_outside_the_emit_lock():
    """Pins the RPH302 fix: the datagram is detached under ``_lock`` but
    the kernel send happens after release — a sendto under the emit lock
    would stall every other emitting thread behind socket-buffer
    backpressure."""
    from ringpop_tpu.cli.stats import UDPStatsd

    udp = UDPStatsd("127.0.0.1:9")
    sent = []

    class Probe:
        def sendto(self, payload, addr):
            assert not udp._lock.locked(), "sendto under the emit lock"
            sent.append(payload)

        def close(self):
            pass

    udp._sock.close()
    udp._sock = Probe()
    udp.incr("a", 1)  # epoch-0 last_flush: the first emit flushes at once
    udp.flush()  # explicit-flush path (empty buffer: no datagram)
    udp.gauge("b", 2.0)  # buffered inside the flush window
    udp.close()  # close drains the tail outside the lock too
    assert sent == [b"a:1|c", b"b:2.0|g"]
    udp.incr("late", 1)  # post-close: dropped, not sent
    assert len(sent) == 2


def test_simbench_telemetry_flag_writes_parseable_journal(tmp_path):
    """The CLI seam end to end: `simbench --telemetry` produces a journal
    with a header per scenario and parseable block records."""
    import subprocess
    import sys

    path = str(tmp_path / "bench.jsonl")
    r = subprocess.run(
        [sys.executable, "-m", "ringpop_tpu.cli.simbench", "--cpu",
         "--only", "loss1k", "--telemetry", path],
        capture_output=True, text=True, timeout=600,
    )
    assert r.returncode == 0, r.stderr[-500:]
    result = json.loads(r.stdout.strip().splitlines()[-1])
    assert result["detected"] is True
    records = telemetry.read_journal(path)
    headers = [x for x in records if x["kind"] == "header"]
    blocks = [x for x in records if x["kind"] == "block"]
    assert len(headers) == 1 and headers[0]["scenario"] == "loss1k"
    assert blocks and sum(b["ticks"] for b in blocks) >= result["ticks"]


def test_journal_header_carries_git_commit():
    """r20 satellite: the header names the SOURCE world next to the
    toolchain — journals are provenance-complete without the repo."""
    import subprocess

    from ringpop_tpu.obs.flight import git_commit

    got = git_commit()
    assert got is not None and len(got) == 40
    try:
        import os

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        want = subprocess.run(
            ["git", "rev-parse", "HEAD"], capture_output=True, text=True,
            cwd=repo, timeout=10,
        )
    except (OSError, subprocess.TimeoutExpired):
        want = None
    if want is not None and want.returncode == 0:
        assert got == want.stdout.strip()


def test_journal_header_git_commit_field(tmp_path):
    path = str(tmp_path / "prov.jsonl")
    with telemetry.TelemetryJournal(path) as journal:
        journal.header("lifecycle", "unit", {"n": 8})
    head = telemetry.read_journal(path)[0]
    assert "git_commit" in head
    from ringpop_tpu.obs.flight import git_commit

    assert head["git_commit"] == git_commit()


def test_live_plane_and_flight_recorder_bit_transparent(tmp_path):
    """The r20 acceptance bar: a run with the WHOLE live plane attached
    — AggregatingStats fed by every block, a FlightRecorder ring, a
    serving HTTP endpoint, a span-tracer sink on the journal — ends
    bit-identical to a bare telemetry-off run.  The live plane only
    READS fetched records; nothing feeds back."""
    import urllib.request

    from ringpop_tpu.obs.endpoint import LiveOps
    from ringpop_tpu.obs.flight import FlightRecorder

    n = 96
    victims, faults = _faults(n, seed=5)

    # bare run: no telemetry at all
    bare = lifecycle.LifecycleSim(n=n, k=32, seed=5, suspect_ticks=8)
    bare.run(32, faults)

    # fully instrumented run
    recorder = FlightRecorder(
        capacity=64, rank=0, path=str(tmp_path / "fl.jsonl")
    )
    ops = LiveOps(0, 1, recorder=recorder)
    path = str(tmp_path / "live.jsonl")
    with telemetry.TelemetryJournal(path) as journal:
        journal.header("lifecycle", "live-transparency", {"n": n})
        sink = telemetry.TelemetrySink(journal=journal, fn=ops.block_record)
        live = lifecycle.LifecycleSim(
            n=n, k=32, seed=5, suspect_ticks=8, telemetry=sink
        )
        addr = ops.serve()
        live.run(32, faults)
        ops.progress(32, 32)
        body = urllib.request.urlopen(
            f"http://{addr}/metrics", timeout=5
        ).read().decode()
    ops.close()

    assert _leaves_equal(bare.state, live.state)
    assert int(telemetry.tree_digest(bare.state)) == int(
        telemetry.tree_digest(live.state)
    )
    # the plane actually observed the run while staying transparent
    assert 'ringpop_sim_ping_send{rank="0"}' in body
    assert any(r.get("kind") == "block" for r in recorder.records())
    agg_total = ops.stats.snapshot()["counters"]["ringpop.sim.ping.send"]
    journal_total = sum(
        r["ping_send"] for r in telemetry.read_journal(path)
        if r["kind"] == "block"
    )
    assert agg_total == journal_total
