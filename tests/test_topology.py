"""Topology-realistic fault overlays (sim/topology.py): the compiler,
the tier-loss evaluation inside the jitted step, the traced suspicion
timeout, per-tier telemetry/scoring, and the constant-topology identity
contracts.

The load-bearing pins:

* a penalty-free tree compiles to NO tier legs and traces to the
  IDENTICAL jaxpr as the flat fault-plan step (no golden recapture);
* a 2-zone tree's partition compiles bit-identical to the hand-built
  symmetric-partition FaultPlan;
* zero-table tier legs (the stacked-fleet default) are bit-transparent
  — a flat member in a topology fleet reproduces its solo run exactly;
* the traced ``suspect_ticks`` leg at B=1 is bit-identical to the
  static path, and batches the timeout axis through the fleet;
* the per-tier suspicion split distinguishes a zone cut from the same
  number of independent crashes.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ringpop_tpu.sim import chaos, delta, lifecycle, scenarios, telemetry, topology
from ringpop_tpu.sim.chaos import FaultPlan
from ringpop_tpu.sim.delta import N_TIERS, TIER_LEVELS
from ringpop_tpu.sim.montecarlo import MonteCarlo
from ringpop_tpu.sim.topology import TierLink, TopologySpec

N, K = 128, 16
PARAMS = dict(n=N, k=K, suspect_ticks=6, rng="counter")


def _digest(state) -> int:
    return int(telemetry.tree_digest(state))


def _stepper(params):
    return jax.jit(functools.partial(lifecycle.step, params))


# -- the compiler -------------------------------------------------------------


def test_compile_blocked_contiguous_ids():
    topo = topology.compile_topology(
        TopologySpec(regions=2, zones_per_region=2, racks_per_zone=2), N
    )
    rack, zone, region = topo.tier_ids
    assert topo.tier_ids.shape == (TIER_LEVELS, N)
    assert topo.tier_ids.dtype == np.int32
    # contiguous equal blocks per level, global ids
    assert np.all(np.diff(rack) >= 0) and len(np.unique(rack)) == 8
    assert np.all(np.diff(zone) >= 0) and len(np.unique(zone)) == 4
    assert np.all(np.diff(region) >= 0) and len(np.unique(region)) == 2
    # the tree property: same rack => same zone => same region
    for r in range(8):
        nodes = topo.nodes_in_rack(r)
        assert len(np.unique(zone[nodes])) == 1
        assert len(np.unique(region[nodes])) == 1
    # equal blocks at this divisible size
    assert all(topo.nodes_in_rack(r).size == N // 8 for r in range(8))


def test_tier_table_monotone_and_models_late_acks():
    spec = TopologySpec(
        regions=2, zones_per_region=2, racks_per_zone=2,
        rack_link=TierLink(rtt_ms=0.2, loss=0.0),
        zone_link=TierLink(rtt_ms=2.0, loss=0.005),
        region_link=TierLink(rtt_ms=60.0, loss=0.02),
        probe_timeout_ms=400.0,
    )
    topo = topology.compile_topology(spec, N)
    table = topo.tier_drop.astype(np.float64)
    assert table[0] == 0.0  # same rack pays nothing
    assert np.all(np.diff(table) >= 0)  # more boundaries, more loss
    # cross-region pays the WAN loss (2 traversals) AND the late-ack tail
    loss_only = 1.0 - (1 - 0.005) ** 2 * (1 - 0.02) ** 2
    assert table[3] > loss_only
    # the late-ack model itself
    assert topology.late_ack_prob(0.0, 400.0) == 0.0
    assert 0.0 < topology.late_ack_prob(100.0, 400.0) < 0.05
    assert topology.late_ack_prob(1e9, 400.0) > 0.99


def test_tier_of_pair_host_mirror_matches_device():
    topo = topology.default_topology(N)
    rng = np.random.default_rng(0)
    a = rng.integers(0, N, size=64).astype(np.int32)
    b = rng.integers(0, N, size=64).astype(np.int32)
    faults = delta.DeltaFaults(
        tier_ids=jnp.asarray(topo.tier_ids), tier_drop=jnp.asarray(topo.tier_drop)
    )
    dev = np.asarray(delta.tier_pair(faults, jnp.asarray(a), jnp.asarray(b)))
    np.testing.assert_array_equal(dev, topo.tier_of_pair(a, b))
    # and the one-hot table expansion
    drop = np.asarray(delta.tier_pair_drop(faults, jnp.asarray(a), jnp.asarray(b)))
    np.testing.assert_allclose(drop, topo.tier_drop[topo.tier_of_pair(a, b)])


def test_compile_refuses_bad_specs():
    with pytest.raises(ValueError, match="empty racks"):
        topology.compile_topology(
            TopologySpec(regions=4, zones_per_region=4, racks_per_zone=4), 32
        )
    with pytest.raises(ValueError, match="loss"):
        topology.compile_topology(
            TopologySpec(zone_link=TierLink(loss=1.5)), N
        )
    with pytest.raises(ValueError, match="rtt_ms"):
        topology.compile_topology(
            TopologySpec(zone_link=TierLink(rtt_ms=-1.0)), N
        )


# -- the identity contracts ---------------------------------------------------


def test_constant_topology_traces_identical_jaxpr():
    """A penalty-free tree emits NO tier legs, so its scenario traces to
    the IDENTICAL jaxpr as the hand-built flat fault-plan step — the
    acceptance-bar identity (no golden recapture)."""
    params = lifecycle.LifecycleParams(**PARAMS)
    state = lifecycle.init_state(params, seed=0)
    flat_topo = topology.compile_topology(
        TopologySpec(regions=2, zones_per_region=2, racks_per_zone=2), N
    )
    assert not flat_topo.has_penalties()
    assert all(v is None for v in flat_topo.plan_legs())
    const_plan = topology.topo_scenario_plan("flat", N, seed=0, horizon=64)
    hand_plan = topology.zone_loss_plan(flat_topo, zone=1, at=2, heal=32)
    ja = jax.make_jaxpr(lambda s, p: lifecycle.step(params, s, p))(state, const_plan)
    # different window constants are still the same jaxpr STRUCTURE; use
    # the same schedule for literal string identity
    hand_same = topology.zone_loss_plan(
        flat_topo, zone=1, at=max(4, 64 // 32), heal=32
    )
    jb = jax.make_jaxpr(lambda s, p: lifecycle.step(params, s, p))(state, hand_same)
    assert str(ja) == str(jb)
    # delta engine too
    dparams = delta.DeltaParams(n=N, k=K, rng="counter")
    dstate = delta.init_state(dparams, seed=0)
    da = jax.make_jaxpr(lambda s, p: delta.step(dparams, s, p))(dstate, const_plan)
    db = jax.make_jaxpr(lambda s, p: delta.step(dparams, s, p))(dstate, hand_same)
    assert str(da) == str(db)


def test_two_zone_tree_partition_equals_hand_built_plan():
    """The topology-equivalence pin: a 2-zone tree with no inter-tier
    penalties compiles its zone partition to a plan bit-identical to the
    hand-built symmetric-partition FaultPlan."""
    topo = topology.compile_topology(
        TopologySpec(regions=1, zones_per_region=2, racks_per_zone=1), N
    )
    got = topology.partition_plan(
        topo, level="zone", cut=(1,), split_at=8, heal_at=64
    )
    group = np.zeros(N, np.int32)
    group[N // 2:] = 1
    want = FaultPlan(
        group=jnp.asarray(group),
        part_from=jnp.asarray(np.int32(8)),
        part_until=jnp.asarray(np.int32(64)),
    )
    for field in FaultPlan._fields:
        g, w = getattr(got, field), getattr(want, field)
        assert (g is None) == (w is None), field
        if g is not None:
            assert g.dtype == w.dtype, field
            np.testing.assert_array_equal(np.asarray(g), np.asarray(w), err_msg=field)


def test_zero_table_tier_legs_are_bit_transparent():
    """Tier legs PRESENT with an all-zero table (``plan_legs(force=True)``
    — the stacked-fleet default shape) must be value-transparent: the
    tier coin is its own draw site, so the trajectory is bit-identical
    to the leg-free run."""
    params = lifecycle.LifecycleParams(**PARAMS)
    flat_topo = topology.compile_topology(
        TopologySpec(regions=2, zones_per_region=2, racks_per_zone=2), N
    )
    base = topology.zone_loss_plan(flat_topo, zone=1, at=4, heal=32)
    with_legs = chaos._merge_plans(base, flat_topo.plan_legs(force=True))
    assert with_legs.tier_ids is not None
    st = _stepper(params)
    s1 = s2 = lifecycle.init_state(params, seed=3)
    for _ in range(40):
        s1 = st(s1, base)
        s2 = st(s2, with_legs)
    assert _digest(s1) == _digest(s2)


def test_penalized_tiers_actually_drop_cross_boundary_legs():
    """A saturated cross-zone tier (drop 1.0) must sever every
    cross-zone exchange while same-zone traffic flows — checked through
    the delta engine's coverage: rumors seeded in zone 0 never reach
    zone 1."""
    topo = topology.compile_topology(
        TopologySpec(regions=1, zones_per_region=2, racks_per_zone=1), N
    )
    plan = FaultPlan(
        tier_ids=jnp.asarray(topo.tier_ids),
        tier_drop=jnp.asarray(np.asarray([0.0, 0.0, 1.0, 1.0], np.float32)),
    )
    params = delta.DeltaParams(n=N, k=K, rng="counter")
    # all K rumors seeded in zone 0 (nodes 0..N/2)
    state = delta.init_state(params, seed=0, sources=np.arange(K) % (N // 2))
    step = jax.jit(functools.partial(delta.step, params))
    for _ in range(64):
        state = step(state, plan)
    learned = np.asarray(
        (state.learned[:, 0] != 0)  # K=16 fits one word: any bit learned
    )
    assert learned[: N // 2].all(), "same-zone dissemination must complete"
    assert not learned[N // 2:].any(), "a 1.0 cross-zone tier must sever the zones"
    # heal the tier: coverage completes
    healed = plan._replace(tier_drop=jnp.zeros(N_TIERS, jnp.float32))
    for _ in range(64):
        state = step(state, healed)
    assert float(delta.converged_fraction(state)) == 1.0


def test_tier_legs_refuse_threefry():
    params = lifecycle.LifecycleParams(n=N, k=K, suspect_ticks=6)  # threefry
    topo = topology.default_topology(N)
    plan = topo.plan_legs(force=True)
    with pytest.raises(ValueError, match="counter"):
        lifecycle.step(params, lifecycle.init_state(params, seed=0), plan)
    dparams = delta.DeltaParams(n=N, k=K)
    with pytest.raises(ValueError, match="counter"):
        delta.step(dparams, delta.init_state(dparams, seed=0), plan)


def test_unpaired_tier_legs_refused():
    topo = topology.default_topology(N)
    with pytest.raises(ValueError, match="pair"):
        chaos.validate_plan(FaultPlan(tier_ids=jnp.asarray(topo.tier_ids)))
    params = lifecycle.LifecycleParams(**PARAMS)
    with pytest.raises(ValueError, match="pair"):
        lifecycle.step(
            params,
            lifecycle.init_state(params, seed=0),
            delta.DeltaFaults(tier_drop=jnp.zeros(N_TIERS, jnp.float32)),
        )


def test_fullview_and_multihost_refuse_topology_legs():
    from ringpop_tpu.sim.fullview import as_fullview_faults

    topo = topology.default_topology(N)
    legs = topo.plan_legs(force=True)
    faults = chaos.faults_at(legs, jnp.int32(0))
    with pytest.raises(ValueError, match="topology"):
        as_fullview_faults(faults)
    with pytest.raises(ValueError, match="fullview"):
        as_fullview_faults(delta.DeltaFaults(suspect_ticks=jnp.asarray(5, jnp.int32)))

    from ringpop_tpu.sim.delta_multihost import _check_supported

    dparams = delta.DeltaParams(n=N, k=K, rng="counter")
    with pytest.raises(NotImplementedError, match="mesh path"):
        _check_supported(dparams, faults)


# -- the traced suspicion timeout (satellite 1) -------------------------------


def test_traced_suspect_ticks_bit_identical_to_static_at_b1():
    """The leg carrying the SAME value as the param, and the -1
    sentinel, must both reproduce the static path bit-for-bit; a
    different value must genuinely move the trajectory."""
    params = lifecycle.LifecycleParams(**PARAMS)
    up = np.ones(N, bool)
    up[[3, 9]] = False
    base = FaultPlan(base_up=jnp.asarray(up))
    same = chaos._merge_plans(
        base, FaultPlan(suspect_ticks=jnp.asarray(params.suspect_ticks, jnp.int32))
    )
    sentinel = chaos._merge_plans(
        base, FaultPlan(suspect_ticks=jnp.asarray(-1, jnp.int32))
    )
    longer = chaos._merge_plans(
        base, FaultPlan(suspect_ticks=jnp.asarray(20, jnp.int32))
    )
    st = _stepper(params)
    s0 = s1 = s2 = s3 = lifecycle.init_state(params, seed=1)
    for _ in range(40):
        s0 = st(s0, base)
        s1 = st(s1, same)
        s2 = st(s2, sentinel)
        s3 = st(s3, longer)
    assert _digest(s0) == _digest(s1) == _digest(s2)
    assert _digest(s0) != _digest(s3)
    # None leg traces to the IDENTICAL static jaxpr
    state = lifecycle.init_state(params, seed=1)
    ja = jax.make_jaxpr(lambda s, p: lifecycle.step(params, s, p))(state, base)
    jb = jax.make_jaxpr(
        lambda s, p: lifecycle.step(params, s, p)
    )(state, FaultPlan(base_up=jnp.asarray(up)))
    assert str(ja) == str(jb)


def test_suspect_ticks_batches_through_the_fleet():
    """The suspects= grid axis: one compiled program, per-member traced
    timeouts — each member bit-identical to the solo static-param run
    (the sweep_static baseline it replaces)."""
    params = lifecycle.LifecycleParams(**PARAMS)
    plan, meta = scenarios.scenario_grid(
        N, victims=[3, 9], doses=[0], losses=(0.0,), suspects=(4, 12),
        churn_seed=1,
    )
    assert [m["suspect"] for m in meta] == [4, 12]
    assert chaos.plan_batch_size(plan) == 2
    seeds = scenarios.grid_seeds(meta, 0)
    mc = MonteCarlo(params, seeds)
    mc.run(48, plan)
    for b, suspect in enumerate((4, 12)):
        solo = lifecycle.LifecycleSim(
            n=N, k=K, seed=seeds[b], suspect_ticks=suspect, rng="counter"
        )
        solo.run(48, chaos.index_plan(plan, b))
        for field in solo.state._fields:
            np.testing.assert_array_equal(
                np.asarray(getattr(mc.states, field))[b],
                np.asarray(getattr(solo.state, field)),
                err_msg=f"b={b} {field}",
            )


def test_suspect_ticks_validation():
    with pytest.raises(ValueError, match="suspect_ticks"):
        chaos.validate_plan(FaultPlan(suspect_ticks=jnp.asarray(0, jnp.int32)))
    with pytest.raises(ValueError, match="suspect_ticks"):
        chaos.validate_plan(FaultPlan(suspect_ticks=jnp.asarray(-3, jnp.int32)))
    chaos.validate_plan(FaultPlan(suspect_ticks=jnp.asarray(-1, jnp.int32)))
    chaos.validate_plan(FaultPlan(suspect_ticks=jnp.asarray(25, jnp.int32)))


# -- plan validation hardening (satellite 2) ----------------------------------


def test_validate_plan_group_range_vs_reach():
    group = np.zeros(N, np.int32)
    group[:4] = 2  # id 2 out of range for a [2, 2] reach
    with pytest.raises(ValueError, match="out of range"):
        chaos.validate_plan(
            FaultPlan(
                group=jnp.asarray(group),
                reach=jnp.asarray(np.eye(2, dtype=bool)),
            )
        )
    # builders route through it too
    with pytest.raises(ValueError, match="out of range"):
        chaos._merge_plans(
            FaultPlan(group=jnp.asarray(group)),
            FaultPlan(reach=jnp.asarray(np.eye(2, dtype=bool))),
        )
    with pytest.raises(ValueError, match="out of range"):
        chaos.stack_plans(
            [FaultPlan(
                group=jnp.asarray(group),
                reach=jnp.asarray(np.eye(2, dtype=bool)),
            )]
        )


def test_validate_plan_reach_shape_and_dtype():
    with pytest.raises(ValueError, match="square"):
        chaos.validate_plan(FaultPlan(reach=jnp.asarray(np.ones((2, 3), bool))))
    with pytest.raises(ValueError, match="boolean"):
        chaos.validate_plan(FaultPlan(reach=jnp.asarray(np.eye(2, dtype=np.float32))))
    with pytest.raises(ValueError, match=">= -1"):
        chaos.validate_plan(FaultPlan(group=jnp.asarray(np.full(N, -2, np.int32))))
    # a in-range directed plan passes
    chaos.validate_plan(chaos.asym_partition_plan(N))


def test_validate_plan_tier_shapes():
    topo = topology.default_topology(N)
    with pytest.raises(ValueError, match="hierarchy"):
        chaos.validate_plan(
            FaultPlan(
                tier_ids=jnp.asarray(topo.tier_ids[:2]),
                tier_drop=jnp.asarray(topo.tier_drop),
            )
        )
    with pytest.raises(ValueError, match="per tier"):
        chaos.validate_plan(
            FaultPlan(
                tier_ids=jnp.asarray(topo.tier_ids),
                tier_drop=jnp.zeros(3, jnp.float32),
            )
        )
    with pytest.raises(ValueError, match="probabilities"):
        chaos.validate_plan(
            FaultPlan(
                tier_ids=jnp.asarray(topo.tier_ids),
                tier_drop=jnp.full(N_TIERS, 1.5, jnp.float32),
            )
        )


# -- stacking through the fleet -----------------------------------------------


def test_flat_member_in_topology_fleet_reproduces_solo():
    """The key stacked-default property: a member WITHOUT topology legs,
    stacked next to a penalized topology member, materializes zero-table
    legs — and must still reproduce its solo trajectory bit-for-bit."""
    params = lifecycle.LifecycleParams(**PARAMS)
    lean = chaos.churn_plan(N, n_churn=4, n_permanent=2, first=4, waves=2, seed=3)
    rich = topology.topo_scenario_plan("zone_loss", N, horizon=64)
    stacked = chaos.stack_plans([lean, rich])
    assert stacked.tier_ids is not None  # materialized for both members
    np.testing.assert_array_equal(
        np.asarray(stacked.tier_drop[0]), np.zeros(N_TIERS, np.float32)
    )
    mc = MonteCarlo(params, [5, 6])
    mc.run(24, stacked)
    solo = lifecycle.LifecycleSim(seed=5, **PARAMS)
    solo.run(24, lean)
    for field in solo.state._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(mc.states, field))[0],
            np.asarray(getattr(solo.state, field)),
            err_msg=field,
        )


def test_topology_member_b1_identical_to_solo():
    params = lifecycle.LifecycleParams(**PARAMS)
    plan = topology.topo_scenario_plan("smoke", N, seed=0, horizon=64)
    mc = MonteCarlo(params, [7])
    mc.run(32, chaos.stack_plans([plan]))
    solo = lifecycle.LifecycleSim(seed=7, **PARAMS)
    solo.run(32, plan)
    assert _digest(jax.tree.map(lambda x: x[0], mc.states)) == _digest(solo.state)


# -- per-tier telemetry + scoring (the acceptance split) ----------------------


def test_per_tier_counters_match_host_expectation():
    """With ONLY a saturated cross-region tier and every node up, all
    suspicion flow is (a) false-positive by plan truth and (b) strictly
    cross-region — the counters must land in exactly that bucket."""
    topo = topology.compile_topology(
        TopologySpec(regions=2, zones_per_region=1, racks_per_zone=1), N
    )
    plan = FaultPlan(
        tier_ids=jnp.asarray(topo.tier_ids),
        tier_drop=jnp.asarray(np.asarray([0.0, 0.0, 0.0, 0.9], np.float32)),
    )
    sink = telemetry.TelemetrySink()
    sim = lifecycle.LifecycleSim(
        seed=0, telemetry=sink, telemetry_tiers=True, **PARAMS
    )
    for _ in range(4):
        sim.run(16, plan)
    recs = sink.records
    total = {
        key: sum(r[f"suspects_{key}"] for r in recs) for key in telemetry.TIER_KEYS
    }
    false_total = {
        key: sum(r[f"false_suspects_{key}"] for r in recs)
        for key in telemetry.TIER_KEYS
    }
    assert total["cross_region"] > 0, "a 0.9 WAN tier must raise suspicions"
    assert total["same_rack"] == total["cross_rack"] == total["cross_zone"] == 0
    # every node is up, so every declaration is a false positive
    assert false_total == total
    # and the score record carries the split
    score = chaos.score_blocks(recs, plan, n=N, scenario="t")
    assert score["suspects_by_tier"]["cross_region"] == total["cross_region"]
    assert score["false_positive_by_tier"] == {
        k: int(v) for k, v in false_total.items()
    }


def test_tiers_unarmed_means_no_tier_keys():
    plan = topology.topo_scenario_plan("zone_loss", N, horizon=64)
    sink = telemetry.TelemetrySink()
    sim = lifecycle.LifecycleSim(seed=0, telemetry=sink, **PARAMS)  # unarmed
    sim.run(16, plan)
    assert "suspects_same_rack" not in sink.records[0]
    score = chaos.score_blocks(sink.records, plan, n=N, scenario="t")
    assert "suspects_by_tier" not in score


def test_zone_loss_distinguished_from_independent_crashes():
    """The acceptance discriminator at test scale: a zone cut's
    suspicion flow has NO near-tier (same-rack/cross-rack) component —
    its same-zone observers are dead — while the same number of
    independent crashes draws near-tier suspicion."""
    n = 256
    topo = topology.default_topology(n)
    horizon = 128
    plans = [
        chaos._merge_plans(
            topology.zone_loss_plan(topo, 1, at=4, heal=horizon // 2),
            topo.plan_legs(),
        ),
        chaos._merge_plans(
            topology.independent_crash_plan(
                topo, int(topo.nodes_in_zone(1).size), at=4, heal=horizon // 2,
                seed=0,
            ),
            topo.plan_legs(),
        ),
    ]
    meta = [
        {"scenario_id": 0, "event": "zone_loss"},
        {"scenario_id": 1, "event": "independent"},
    ]
    params = lifecycle.LifecycleParams(n=n, k=32, suspect_ticks=8, rng="counter")
    scores = scenarios.scored_fleet(
        params, chaos.stack_plans(plans), meta, [0, 1], horizon=horizon,
        journal_every=16, scenario="topo_test",
    )
    for s in scores:
        assert isinstance(s["suspects_by_tier"], dict)
        assert isinstance(s["time_to_detect_by_tier"], dict)

    def near(s):
        by_tier = s["suspects_by_tier"]
        return by_tier["same_rack"] + by_tier["cross_rack"]

    def total(s):
        return sum(s["suspects_by_tier"].values())

    assert total(scores[0]) > 0 and total(scores[1]) > 0
    zone_share = near(scores[0]) / total(scores[0])
    ind_share = near(scores[1]) / total(scores[1])
    assert ind_share > zone_share, (zone_share, ind_share)
    assert zone_share == 0.0, "a zone cut has no live near-tier accusers"


def test_wan_oneway_refutations_attributed_to_unreachable_direction():
    """The topology WAN builder rides the asym reach semantics: the cut
    region is unreachable from outside, so its (false) accusations
    refute there — the per-direction split must say so."""
    n = 256
    plan = topology.topo_scenario_plan("wan", n, seed=1, horizon=128)
    sink = telemetry.TelemetrySink()
    sim = lifecycle.LifecycleSim(
        n=n, k=32, seed=2, suspect_ticks=5, rng="counter", telemetry=sink,
        telemetry_tiers=True,
    )
    for _ in range(8):
        sim.run(16, plan)
    score = chaos.score_blocks(sink.records, plan, n=n, scenario="wan")
    assert score["refutations"] > 0, "the one-way window must generate refutes"
    assert (
        score["refutations_unreachable_dir"] + score["refutations_reachable_dir"]
        == score["refutations"]
    )
    assert score["refutations_unreachable_dir"] > score["refutations_reachable_dir"]


def test_symmetric_member_in_directed_fleet_reports_no_direction():
    """The stacked identity-reach default is MUTUAL blockage — a
    symmetric partition has no unreachable direction, so a symmetric
    member stacked next to a one-way member must report
    refuted_unreachable_dir == 0 (every refutation lands in the
    reachable bucket), not claim a direction it doesn't have."""
    n = 256
    topo = topology.default_topology(n)
    sym = chaos._merge_plans(
        topology.partition_plan(topo, level="region", cut=(1,), split_at=4,
                                heal_at=48),
        chaos.churn_plan(n, n_churn=4, n_permanent=0, first=2, stagger=1,
                         waves=1, down_ticks=16, seed=1),
    )
    oneway = topology.partition_plan(
        topo, level="region", cut=(1,), split_at=4, heal_at=48, one_way=True
    )
    stacked = chaos.stack_plans([sym, oneway])
    params = lifecycle.LifecycleParams(n=n, k=32, suspect_ticks=5, rng="counter")
    mc = MonteCarlo(params, [0, 1], telemetry=True)
    recs = []
    for _ in range(6):
        mc.run(16, stacked)
        recs.extend(mc.fetch_telemetry(stacked))
    sym_blocks = [r for r in recs if r["scenario_id"] == 0]
    ow_blocks = [r for r in recs if r["scenario_id"] == 1]
    assert all(r["refuted_unreachable_dir"] == 0 for r in sym_blocks)
    assert sum(r["refuted_reachable_dir"] for r in sym_blocks) > 0
    # the one-way member still attributes to its sink side
    assert sum(r["refuted_unreachable_dir"] for r in ow_blocks) > 0


def test_emit_topo_stats_gauges():
    class Rec:
        def __init__(self):
            self.gauges = {}

        def gauge(self, key, value):
            self.gauges[key] = value

    score = {
        "suspects_by_tier": {"same_rack": 0, "cross_zone": 5},
        "false_positive_by_tier": {"cross_zone": 2},
        "time_to_detect_by_tier": {"cross_zone": 16, "same_rack": None},
        "refutations_unreachable_dir": 7,
    }
    rec = Rec()
    topology.emit_topo_stats(rec, score)
    assert rec.gauges["ringpop.sim.topo.suspects.cross-zone"] == 5.0
    assert rec.gauges["ringpop.sim.topo.false-positives.cross-zone"] == 2.0
    assert rec.gauges["ringpop.sim.topo.time-to-detect.cross-zone"] == 16.0
    assert rec.gauges["ringpop.sim.topo.refuted.unreachable-dir"] == 7.0
    assert "ringpop.sim.topo.time-to-detect.same-rack" not in rec.gauges


# -- scenario builders --------------------------------------------------------


def test_correlated_builders_shapes():
    topo = topology.default_topology(N)
    zl = topology.zone_loss_plan(topo, 1, at=8, heal=32)
    nodes = topo.nodes_in_zone(1)
    crash = np.asarray(zl.crash_tick)
    assert (crash[nodes] == 8).all()
    assert (crash[np.setdiff1d(np.arange(N), nodes)] == chaos.NO_TICK).all()
    # switch flap: ONE unit — identical period AND phase behind the switch
    sf = topology.switch_flap_plan(topo, 2, period=24, down=6, start=8)
    rnodes = topo.nodes_in_rack(2)
    assert len(np.unique(np.asarray(sf.flap_phase)[rnodes])) == 1
    assert (np.asarray(sf.flap_period)[rnodes] == 24).all()
    # first down window opens at start
    up9 = chaos.up_at_host(sf, 7, N)
    up8 = chaos.up_at_host(sf, 8, N)
    assert up9[rnodes].all() and not up8[rnodes].any()
    # partition builder refuses nonsense
    with pytest.raises(ValueError, match="do not exist"):
        topology.partition_plan(topo, level="zone", cut=(99,))
    with pytest.raises(ValueError, match="nothing"):
        topology.partition_plan(topo, level="region", cut=(0, 1))
    with pytest.raises(ValueError, match="level"):
        topology.partition_plan(topo, level="pod", cut=(0,))
    with pytest.raises(ValueError, match="does not exist"):
        topology.zone_loss_plan(topo, 99)


def test_topo_scenario_specs_family():
    topo = topology.default_topology(N)
    plans, meta = topology.topo_scenario_specs(topo, seed=0, horizon=128, reps=2)
    assert len(plans) == len(meta) == 2 * (4 + 8 + 2 + 4)
    events = {m["event"] for m in meta}
    assert events == {"zone_loss", "switch_flap", "wan", "wan_oneway", "independent"}
    # stacks cleanly (the fleet shape)
    stacked = chaos.stack_plans(plans)
    assert chaos.plan_batch_size(stacked) == len(plans)
    # every member carries the tier legs (the default tree is penalized)
    assert stacked.tier_ids is not None and stacked.tier_drop is not None


def test_scenario_grid_overlay_axis():
    topo = topology.default_topology(N)
    overlay = chaos._merge_plans(
        topology.zone_loss_plan(topo, 1, at=4, heal=32), topo.plan_legs()
    )
    plan, meta = scenarios.scenario_grid(
        N, victims=[3], doses=[0, 2], losses=(0.0,),
        overlays=(("none", None), ("zone_loss", overlay)), churn_seed=1,
    )
    assert chaos.plan_batch_size(plan) == 4
    assert [m["overlay"] for m in meta] == ["none", "none", "zone_loss", "zone_loss"]
    # overlay members carry the topology legs; the stacked default zeros
    # the others
    np.testing.assert_array_equal(
        np.asarray(plan.tier_drop[0]), np.zeros(N_TIERS, np.float32)
    )
    assert float(np.asarray(plan.tier_drop[2]).max()) > 0
    # a colliding overlay (partition vs parts>0) is refused loudly
    with pytest.raises(ValueError, match="more than one plan"):
        scenarios.scenario_grid(
            N, victims=[3], doses=[0], parts=(0.5,),
            overlays=(("wan", topology.partition_plan(topo, level="region", cut=(1,))),),
            churn_seed=1,
        )
