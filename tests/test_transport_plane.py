"""r21 "one transport plane" suite.

Pins the tentpole's two halves and the satellites:

* registered-buffer zero-copy — the shm ring hands the collector
  READ-ONLY VIEWS of its slots (``np.shares_memory`` proof, not a
  counter claim), the slot is not republished until the dispatch's
  staging gather consumed it, and the merged ledger's ``copy_bytes``
  reads 0 for the shm→dispatch path;
* slot lifetime under the zero-copy protocol — seq-word wrap-around, a
  dispatch still holding a slot view when the frontend retries (the
  responder's rescan), STATUS_ERR republication under a mid-scan
  exception (the r13 poison pin, extended to views);
* pooled receive buffers — ``_recv_exact``'s allocation-count pin (the
  per-frame ``bytearray`` can't regress back);
* the merged ``TransportLedger`` — per-class sums equal the legacy
  per-transport ledgers on identical traffic (exchange == the fabric's
  ``wire_stats``; rpc == the channel's legacy body counters + the
  16 B/frame fabric header);
* the deduped codec stack — channel body/array wire bytes unchanged
  (round-trip + exact-bytes pins on the thin JSON/base64 leg).
"""

import asyncio
import base64
import json
import socket
import struct
import threading
import time

import numpy as np
import pytest

from ringpop_tpu.net.channel import (
    MAX_FRAME_BYTES,
    TCPChannel,
    _decode_frame_body,
    _frame_bytes,
    _msgpack_frame_bytes,
    decode_array,
    encode_array,
)
from ringpop_tpu.parallel.fabric import (
    _HDR,
    RECV_ALLOCS,
    Fabric,
    LocalKV,
    RpcEndpoint,
    TransportLedger,
    _recv_exact,
    frame_array,
)
from ringpop_tpu.serve import shm as shm_mod
from ringpop_tpu.serve.shm import (
    _COUNT,
    _GEN,
    _N,
    _REQ_SEQ,
    _RESP_SEQ,
    _STATUS,
    STATUS_ERR,
    STATUS_OK,
    ShmClient,
    ShmRing,
    ShmServer,
)


def _run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


class _CapturingService:
    """RingService stand-in: records what the shm server hands it.

    ``scan()`` routes a lone small request (count <= 64, nothing queued)
    through ``dispatch_direct`` and everything else through
    ``submit_nowait`` + ``flush_now`` — tests that want the collector
    lane post > 64 hashes."""

    def __init__(self):
        self._pending = []  # (hashes, n, callback) awaiting flush
        self.submitted = []  # every submit_nowait ever, same triples
        self.direct = []  # every dispatch_direct
        self.answer_on_flush = True
        self.raise_on_flush = None

    def submit_nowait(self, hashes, n, callback, loop=None):
        self.submitted.append((hashes, n, callback))
        self._pending.append((hashes, n, callback))

    def flush_now(self):
        if self.raise_on_flush is not None:
            raise self.raise_on_flush
        if not self.answer_on_flush:
            return  # dispatch "holds" the slot views
        pend, self._pending[:] = list(self._pending), []
        for hashes, n, cb in pend:
            cb(np.zeros(len(hashes) * n, np.int32), 7)

    def dispatch_direct(self, hashes, n, callback):
        self.direct.append((hashes, n, callback))
        callback(np.zeros(len(hashes) * n, np.int32), 7)


def _post(server: ShmServer, slot: int, hashes: np.ndarray, n: int = 1) -> int:
    """Write a request into a slot the way ShmClient does; returns req."""
    ring = server.ring
    hdr = ring._headers[slot]
    ring._hashes[slot][: len(hashes)] = hashes
    hdr[_COUNT] = np.uint32(len(hashes))
    hdr[_N] = np.uint32(n)
    req = (int(hdr[_REQ_SEQ]) + 1) & 0xFFFFFFFF
    hdr[_REQ_SEQ] = np.uint32(req)
    return req


# -- registered-buffer zero-copy ---------------------------------------------


def test_shm_scan_hands_collector_a_shared_readonly_view():
    """The shm→dispatch hand-off is ZERO-copy, proven by aliasing: the
    array the collector receives shares memory with the ring segment, is
    read-only, and the ledger's copy_bytes stays 0."""
    svc = _CapturingService()
    server = ShmServer(svc, slots=2, key_cap=256, max_n=2)
    try:
        hashes = np.arange(100, dtype=np.uint32) + 5
        _post(server, 0, hashes)
        assert server.scan() == 1
        (got, n, _cb), = svc.submitted
        assert n == 1
        assert np.shares_memory(got, server.ring._hashes[0])
        assert not got.flags.writeable
        assert np.array_equal(got, hashes)
        row = server.ledger.stats()["classes"]["shm"]
        assert row["copy_bytes"] == 0
        assert row["bytes_recv"] == hashes.nbytes and row["frames_recv"] == 1
        # the responder answered (capturing service answers on flush):
        # the slot republished only AFTER the collector consumed the view
        hdr = server.ring._headers[0]
        assert int(hdr[_RESP_SEQ]) == int(hdr[_REQ_SEQ])
        assert int(hdr[_STATUS]) == STATUS_OK
        assert row["bytes_sent"] == hashes.nbytes and row["frames_sent"] == 1
        del got, hdr  # release segment views so close() can unmap
        svc.submitted.clear()
    finally:
        server.close()


def test_shm_direct_lane_is_zero_copy_too():
    svc = _CapturingService()
    server = ShmServer(svc, slots=1, key_cap=256, max_n=2)
    try:
        _post(server, 0, np.arange(8, dtype=np.uint32), n=2)
        assert server.scan() == 1
        (got, n, _cb), = svc.direct
        assert n == 2 and np.shares_memory(got, server.ring._hashes[0])
        assert not got.flags.writeable
        assert server.ledger.stats()["copy_bytes"] == 0
        del got
        svc.direct.clear()
    finally:
        server.close()


def test_shm_slot_not_republished_until_dispatch_consumes():
    """Explicit lifetime: while the dispatch holds the slot view
    (callback not yet delivered), resp_seq stays unpublished and the
    slot stays in _inflight — the client cannot reuse the buffer under
    the dispatch."""
    svc = _CapturingService()
    svc.answer_on_flush = False  # hold the view
    server = ShmServer(svc, slots=2, key_cap=256, max_n=1)
    try:
        req = _post(server, 0, np.arange(100, dtype=np.uint32))
        server.scan()
        hdr = server.ring._headers[0]
        assert int(hdr[_RESP_SEQ]) != req and 0 in server._inflight
        # ... dispatch completes later:
        (_got, _n, cb), = svc._pending
        svc._pending.clear()
        cb(np.zeros(100, np.int32), 3)
        assert int(hdr[_RESP_SEQ]) == req and 0 not in server._inflight
        assert int(hdr[_GEN]) == 3
        del _got
        svc.submitted.clear()
    finally:
        server.close()


# -- slot lifetime property tests --------------------------------------------


def test_shm_seq_word_wraparound():
    """req_seq is modular uint32: a client sitting at 0xFFFFFFFF must
    wrap to 0 (numpy would raise OverflowError on the naive +1) and the
    whole request/response protocol keeps working across the wrap."""
    svc = _CapturingService()
    server = ShmServer(svc, slots=1, key_cap=64, max_n=1)
    name, sock_path = server.address
    client = ShmClient(name, sock_path, 0, slots=1, key_cap=64, max_n=1,
                       timeout=5.0, spin_us=50.0)
    # park the slot one bump below the wrap
    client._hdr[_REQ_SEQ] = np.uint32(0xFFFFFFFF)
    client._hdr[_RESP_SEQ] = np.uint32(0xFFFFFFFF)

    # fake server loop: answer posted requests like scan+dispatch would
    stop = threading.Event()

    def serve():
        while not stop.is_set():
            if server.scan() == 0:
                time.sleep(0.0005)

    t = threading.Thread(target=serve, daemon=True)
    t.start()
    try:
        for k in range(3):  # crosses the wrap on the first post
            owners, gen = client.lookup_hashes(np.arange(4, dtype=np.uint32))
            assert owners.shape == (4,) and gen == 7
        assert int(client._hdr[_REQ_SEQ]) == 2  # 0xFFFFFFFF -> 0 -> 1 -> 2
    finally:
        stop.set()
        t.join(2)
        client.close()
        server.close()


def test_shm_retry_while_dispatch_holds_slot():
    """A frontend that times out and reposts into its slot while the old
    dispatch still holds the view: the old answer publishes under the
    OLD req (the client ignores it), and the responder's rescan picks up
    the retry even though its wake datagram was already drained."""
    svc = _CapturingService()
    svc.answer_on_flush = False
    server = ShmServer(svc, slots=1, key_cap=256, max_n=1)

    async def main():
        loop = asyncio.get_event_loop()
        server.attach(loop)
        old_req = _post(server, 0, np.arange(100, dtype=np.uint32))
        server.scan()
        assert 0 in server._inflight
        # frontend gives up and retries with DIFFERENT data (no datagram:
        # it was already drained in the real interleaving)
        new_req = _post(server, 0, np.arange(100, dtype=np.uint32) + 7)
        assert new_req != old_req
        # old dispatch finally completes -> responder publishes old req,
        # notices req_seq moved, schedules a rescan on the loop
        (_got, _n, cb), = svc._pending
        svc._pending.clear()
        svc.answer_on_flush = True
        cb(np.zeros(100, np.int32), 7)
        hdr = server.ring._headers[0]
        assert int(hdr[_RESP_SEQ]) == old_req  # stale answer, client ignores
        for _ in range(50):  # let the rescan run
            await asyncio.sleep(0.01)
            if int(hdr[_RESP_SEQ]) == new_req:
                break
        assert int(hdr[_RESP_SEQ]) == new_req, "retry stranded — rescan missing"
        assert len(svc.submitted) == 2
        assert np.array_equal(
            svc.submitted[1][0], np.arange(100, dtype=np.uint32) + 7
        )
        svc.submitted.clear()

    try:
        _run(main())
    finally:
        server._loop = None
        server.close()


def test_shm_status_err_republication_on_mid_scan_exception():
    """The r13 poison pin on the zero-copy path: a collector that blows
    up mid-scan must answer STATUS_ERR on every picked slot (views and
    all), leave nothing in _inflight, and keep serving afterwards."""
    svc = _CapturingService()
    svc.raise_on_flush = RuntimeError("deliberate poison")
    server = ShmServer(svc, slots=2, key_cap=256, max_n=1)
    try:
        r0 = _post(server, 0, np.arange(70, dtype=np.uint32))
        r1 = _post(server, 1, np.arange(70, dtype=np.uint32))
        server.scan()
        for s, req in ((0, r0), (1, r1)):
            hdr = server.ring._headers[s]
            assert int(hdr[_RESP_SEQ]) == req
            assert int(hdr[_STATUS]) == STATUS_ERR
        assert not server._inflight
        # next scan still works
        svc.raise_on_flush = None
        svc._pending.clear()  # the poisoned flush never drained these
        r0b = _post(server, 0, np.arange(70, dtype=np.uint32))
        server.scan()
        hdr = server.ring._headers[0]
        assert int(hdr[_RESP_SEQ]) == r0b and int(hdr[_STATUS]) == STATUS_OK
        del hdr
        svc.submitted.clear()
        svc._pending.clear()
    finally:
        server.close()


# -- pooled receive buffers ---------------------------------------------------


def test_recv_exact_pooled_buffer_allocation_pin():
    """With a pooled buffer, _recv_exact must not allocate per frame —
    the regression this pins out cost one bytearray per received frame."""
    a, b = socket.socketpair()
    try:
        pool = bytearray(1 << 12)
        payload = bytes(range(256)) * 8  # 2 KiB
        base = RECV_ALLOCS.n
        for _ in range(50):
            a.sendall(payload)
            got = _recv_exact(b, len(payload), pool)
            assert bytes(got) == payload
        assert RECV_ALLOCS.n == base, "pooled receive allocated per frame"
        # without a pool (or an undersized one) it must count the alloc
        a.sendall(payload)
        _recv_exact(b, len(payload))
        a.sendall(payload)
        _recv_exact(b, len(payload), bytearray(8))
        assert RECV_ALLOCS.n == base + 2
    finally:
        a.close()
        b.close()


def test_recv_exact_returns_sized_view():
    a, b = socket.socketpair()
    try:
        pool = bytearray(64)
        a.sendall(b"xyz")
        got = _recv_exact(b, 3, pool)
        assert isinstance(got, memoryview) and len(got) == 3
        assert got.obj is pool  # really the pooled storage, no copy
    finally:
        a.close()
        b.close()


# -- merged ledger: per-class sums equal the legacy ledgers -------------------


def test_fabric_ledger_class_equals_legacy_wire_stats():
    """Class "exchange" of the merged ledger mirrors the fabric's legacy
    wire counters at the same accounting sites — equal by construction,
    pinned here on real two-rank traffic."""
    kv = LocalKV()
    out = [None, None]
    errs = []

    def run(rank):
        try:
            with Fabric(rank, 2, kv, namespace="t-ledger",
                        timeout_ms=30_000) as fab:
                peer = 1 - rank
                rng = np.random.default_rng(rank)
                for tick in range(3):
                    arrs = [rng.integers(0, 2**32, 257, dtype=np.uint32)]
                    fab.exchange_async(tick + 1, {peer: arrs}, [peer]).wait()
                out[rank] = (fab.wire_stats(), fab.ledger.stats())
        except BaseException as e:  # surfaces in the main thread's assert
            errs.append(e)

    ts = [threading.Thread(target=run, args=(r,), daemon=True) for r in (0, 1)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(30)
    assert not errs and not any(t.is_alive() for t in ts), errs
    for ws, ls in out:
        row = ls["classes"]["exchange"]
        assert row["bytes_sent"] == ws["bytes_sent"] > 0
        assert row["bytes_recv"] == ws["bytes_recv"] > 0
        assert row["raw_bytes_sent"] == ws["raw_bytes_sent"]
        assert row["raw_bytes_recv"] == ws["raw_bytes_recv"]
        assert row["frames_sent"] == row["frames_recv"] == 3
        assert row["copy_bytes"] == 0


def test_channel_ledger_class_maps_to_legacy_counters():
    """Class "rpc" vs the channel's legacy {bytes_sent, frames_sent}:
    frames match exactly; transport bytes are the legacy body bytes plus
    the 16 B/frame fabric header (the documented migration mapping)."""

    async def main():
        shared = TransportLedger()
        server = TCPChannel(app="srv", ledger=shared)
        server.register("svc", "/echo", lambda b, h: {"x": b.get("x")})
        addr = await server.listen("127.0.0.1", 0)
        client = TCPChannel(app="cli", ledger=shared)
        for i in range(5):
            await client.call(addr, "svc", "/echo", {"x": i}, timeout=5)
        legacy = client.wire_stats(), server.wire_stats()
        row = shared.stats()["classes"]["rpc"]
        await client.close()
        await server.close()
        frames = sum(s["frames_sent"] for s in legacy)
        body_bytes = sum(s["bytes_sent"] for s in legacy)
        assert row["frames_sent"] == frames == 10
        assert row["bytes_sent"] == body_bytes + _HDR.size * frames
        # both endpoints share the ledger, so recv mirrors send exactly
        assert row["frames_recv"] == frames
        assert row["bytes_recv"] == row["bytes_sent"]
        assert row["copy_bytes"] == 0

    _run(main())


def test_ledger_total_sums_classes():
    led = TransportLedger()
    led.add("a", bytes_sent=3, frames_sent=1)
    led.add("b", bytes_sent=5, bytes_recv=2, copy_bytes=4)
    st = led.stats()
    assert st["total"]["bytes_sent"] == 8
    assert st["total"]["bytes_recv"] == 2
    assert st["copy_bytes"] == 4
    assert set(st["classes"]) == {"a", "b"}


# -- the folded channel's wire behavior ---------------------------------------


def test_rpc_frame_wire_format():
    """A channel request on the wire is exactly one fabric _HDR frame
    around the UNCHANGED body encoding — pinned byte-for-byte so a
    desync between folded endpoints can't hide."""
    body = _frame_bytes({"id": 1, "kind": "req", "svc": "s", "ep": "/e",
                         "body": {"x": 1}, "headers": {}})
    # the body leg is the pre-fold json line, byte-identical
    assert body == (
        b'{"id":1,"kind":"req","svc":"s","ep":"/e","body":{"x":1},"headers":{}}\n'
    )
    decoded = _decode_frame_body(memoryview(body))
    assert decoded["id"] == 1 and decoded["body"] == {"x": 1}
    # msgpack leg: 0xC1 magic + uint32-be length + msgpack payload
    mp = _msgpack_frame_bytes({"id": 2, "ok": True})
    assert mp[0] == 0xC1
    ln = int.from_bytes(mp[1:5], "big")
    assert len(mp) == 5 + ln
    assert _decode_frame_body(memoryview(mp)) == {"id": 2, "ok": True}
    # garbage stays garbage
    assert _decode_frame_body(b"") is None
    assert _decode_frame_body(b"\x00junk") is None
    assert _decode_frame_body(b"\xc1\x00\x00\x00\x02\x05") is None  # scalar


@pytest.mark.parametrize("codec", ["json", "msgpack"])
def test_encode_array_thin_wrapper_round_trip(codec):
    """Satellite pin: the array lanes survive the codec dedupe with the
    wire format unchanged — plain lane bytes are exactly tobytes/base64,
    fabric lane bytes are exactly frame_array's."""
    rng = np.random.default_rng(3)
    arr = rng.integers(0, 2**32, 513, dtype=np.uint32)
    plain = encode_array(arr, codec, "<u4")
    if codec == "msgpack":
        assert plain == arr.tobytes()
    else:
        assert plain == base64.b64encode(arr.tobytes()).decode("ascii")
    assert np.array_equal(decode_array(plain, "<u4"), arr)
    fab = encode_array(arr, codec, "<u4", fabric=True)
    raw = fab["_fab"] if codec == "msgpack" else base64.b64decode(fab["_fab"])
    assert bytes(raw) == frame_array(arr)
    assert np.array_equal(decode_array(fab, "<u4"), arr)


def test_channel_close_fails_pending_with_fabric_family():
    """Closing the server while a call is in flight surfaces as the
    fabric error family (the only transport error surface)."""
    from ringpop_tpu.errors import FabricPeerLost

    async def main():
        server = TCPChannel(app="srv")

        async def slow(body, headers):
            await asyncio.sleep(30)
            return {}

        server.register("svc", "/slow", slow)
        addr = await server.listen("127.0.0.1", 0)
        client = TCPChannel(app="cli")
        task = asyncio.ensure_future(
            client.call(addr, "svc", "/slow", {}, timeout=20)
        )
        await asyncio.sleep(0.1)
        await server.close()
        with pytest.raises(FabricPeerLost):
            await task
        await client.close()

    _run(main())


def test_rpc_endpoint_concurrent_requests_demux_by_id():
    """The tagged demux under concurrency: interleaved responses land on
    the right callers (the multiplex the asyncio reader used to do)."""

    async def main():
        server = TCPChannel(app="srv")

        async def echo(body, headers):
            await asyncio.sleep(0.001 * (body["x"] % 5))
            return {"x": body["x"]}

        server.register("svc", "/echo", echo)
        addr = await server.listen("127.0.0.1", 0)
        client = TCPChannel(app="cli")
        res = await asyncio.gather(
            *(client.call(addr, "svc", "/echo", {"x": i}, timeout=10)
              for i in range(40))
        )
        assert [r["x"] for r in res] == list(range(40))
        await client.close()
        await server.close()

    _run(main())
