"""The r17 unified-transport slice: serve TCP framing + shm on the
fabric's codec/error core (one peer-lifecycle/error model, the r15 codec
available to forwarded batches, byte accounting preserved)."""

import asyncio

import numpy as np
import pytest

from ringpop_tpu.net.channel import (
    CallError,
    CallTimeoutError,
    LocalChannel,
    LocalNetwork,
    PeerUnreachableError,
    TCPChannel,
    decode_array,
    encode_array,
)
from ringpop_tpu.parallel.fabric import (
    FabricError,
    FabricPeerLost,
    FabricTimeout,
)


def _run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


# -- one error family ---------------------------------------------------------


def test_frontend_surfaces_stay_jax_free():
    """The unified error family must NOT cost frontends the jax import:
    channel / forward.batch / shm / serve.client import clean in a fresh
    interpreter (the family lives in the import-free ringpop_tpu.errors
    leaf, not parallel.fabric)."""
    import os
    import subprocess
    import sys

    probes = [
        ("import", m, f"import {m}, sys; "
                      "raise SystemExit(1 if 'jax' in sys.modules else 0)")
        for m in (
            "ringpop_tpu.net.channel",
            "ringpop_tpu.forward.batch",
            "ringpop_tpu.serve.shm",
            "ringpop_tpu.serve.client",
            "ringpop_tpu.parallel.fabric",  # numpy-only; parallel/__init__ is lazy
        )
    ] + [
        # the fabric ARRAY LANE must stay jax-free AT RUNTIME too — a
        # frontend decoding a {'_fab': ...} value must not pay (or even
        # need) the jax import
        ("runtime", "fabric array lane",
         "import sys, numpy as np; "
         "from ringpop_tpu.net.channel import encode_array, decode_array; "
         "v = encode_array(np.arange(512, dtype=np.uint32), 'json', fabric=True); "
         "assert (decode_array(v) == np.arange(512)).all(); "
         "raise SystemExit(1 if 'jax' in sys.modules else 0)"),
    ]
    for kind, name, code in probes:
        r = subprocess.run(
            [sys.executable, "-c", code],
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
        assert r.returncode == 0, f"{name} pulled jax ({kind})"


def test_channel_errors_are_fabric_errors():
    """Branching on the fabric family covers every transport: channel
    timeouts ARE FabricTimeout, dead channel peers ARE FabricPeerLost."""
    assert issubclass(CallError, FabricError)
    assert issubclass(CallTimeoutError, FabricTimeout)
    assert issubclass(PeerUnreachableError, FabricPeerLost)


def test_local_network_dead_peer_is_peer_lost():
    net = LocalNetwork()
    chan = LocalChannel(net, "a:1")
    with pytest.raises(FabricPeerLost):
        _run(chan.call("gone:1", "svc", "/ep", {}))


def test_local_network_black_hole_is_fabric_timeout():
    net = LocalNetwork()
    chan = LocalChannel(net, "a:1")
    LocalChannel(net, "b:1").register("svc", "/ep", lambda b, h: {})
    net.black_hole("b:1")
    with pytest.raises(FabricTimeout):
        _run(chan.call("b:1", "svc", "/ep", {}, timeout=0.01))


def test_tcp_connect_refused_is_peer_lost():
    async def main():
        chan = TCPChannel(app="t")
        with pytest.raises(FabricPeerLost):
            await chan.call("127.0.0.1:1", "svc", "/ep", {}, timeout=0.5)

    _run(main())


def test_shm_client_timeout_is_fabric_timeout():
    """A posted slot nobody answers times out as FabricTimeout — the shm
    flavor of a silent fabric peer."""
    import os
    import socket
    import tempfile

    from ringpop_tpu.serve.shm import ShmClient, ShmRing

    ring = ShmRing(slots=2, key_cap=64, max_n=2, create=True)
    sock_path = os.path.join(tempfile.gettempdir(), f"rp-test-{os.getpid()}.sock")
    srv = socket.socket(socket.AF_UNIX, socket.SOCK_DGRAM)
    srv.bind(sock_path)
    try:
        client = ShmClient(ring.name, sock_path, 0, slots=2, key_cap=64,
                           max_n=2, timeout=0.05, spin_us=10.0)
        with pytest.raises(FabricTimeout):
            client.lookup_hashes(np.array([1, 2], np.uint32))
        client.close()
    finally:
        srv.close()
        os.unlink(sock_path)
        ring.close(unlink=True)


def test_shm_client_dead_server_socket_is_peer_lost():
    import os
    import socket
    import tempfile

    from ringpop_tpu.serve.shm import ShmClient, ShmRing

    ring = ShmRing(slots=2, key_cap=64, max_n=2, create=True)
    sock_path = os.path.join(tempfile.gettempdir(), f"rp-dead-{os.getpid()}.sock")
    srv = socket.socket(socket.AF_UNIX, socket.SOCK_DGRAM)
    srv.bind(sock_path)
    client = ShmClient(ring.name, sock_path, 0, slots=2, key_cap=64,
                       max_n=2, timeout=0.05, spin_us=10.0)
    srv.close()
    os.unlink(sock_path)  # the server process "died"
    try:
        with pytest.raises(FabricPeerLost):
            client.lookup_hashes(np.array([1], np.uint32))
    finally:
        client.close()
        ring.close(unlink=True)


# -- the r15 codec on channel arrays ------------------------------------------


@pytest.mark.parametrize("codec", ["json", "msgpack"])
def test_fabric_array_lane_round_trips_bit_identical(codec):
    """Arrays through the fabric lane decode bit-identical under both
    frame codecs, for sparse (ROWS/RUNS-winning) and dense payloads."""
    rng = np.random.default_rng(0)
    sparse = np.zeros((64, 16), np.uint32)
    sparse[3] = rng.integers(0, 2**32, 16, dtype=np.uint32)
    dense1d = rng.integers(0, 2**32, 257, dtype=np.uint32)
    for arr in (sparse.reshape(-1), dense1d, np.zeros(0, np.uint32)):
        val = encode_array(arr, codec, "<u4", fabric=True)
        back = decode_array(val, "<u4")
        assert back.dtype == np.uint32
        assert np.array_equal(back, arr.reshape(-1))
        assert back.tobytes() == arr.tobytes()
    # int32 owner vectors too
    owners = rng.integers(-1, 64, 4096).astype(np.int32)
    back = decode_array(encode_array(owners, codec, "<i4", fabric=True), "<i4")
    assert np.array_equal(back, owners)


def test_fabric_lane_shrinks_sparse_payloads():
    """The accounting contract: a mostly-zero array costs LESS on the
    wire through the fabric lane than the plain lane (the codec engaged),
    and a random dense one costs at most the raw fallback + header."""
    sparse = np.zeros(1 << 14, np.uint32)
    sparse[7] = 123
    plain = encode_array(sparse, "msgpack", "<u4")
    fab = encode_array(sparse, "msgpack", "<u4", fabric=True)
    assert len(fab["_fab"]) < len(plain) / 10
    dense = np.random.default_rng(1).integers(0, 2**32, 1 << 14, dtype=np.uint32)
    fabd = encode_array(dense, "msgpack", "<u4", fabric=True)
    assert len(fabd["_fab"]) <= len(dense.tobytes()) + 64


def test_fabric_lane_through_live_channel_and_forwarder():
    """End-to-end: a BatchForwarder with fabric_arrays=True against an
    unmodified lookup endpoint — the decoder's self-description makes
    the lanes interoperate; answers bit-identical to the plain lane."""
    from ringpop_tpu.forward.batch import BatchForwarder

    net = LocalNetwork()
    srv = LocalChannel(net, "s:1")
    tokens = np.sort(
        np.random.default_rng(2).choice(2**32 - 1, 64, replace=False).astype(np.uint32)
    )
    owners = (np.arange(64) % 8).astype(np.int32)

    async def handle(body, headers):
        h = decode_array(body["h"], "<u4")
        idx = np.searchsorted(tokens, h, side="left")
        idx = np.where(idx >= 64, 0, idx)
        return {"o": encode_array(owners[idx], "json", "<i4"), "gen": 1}

    srv.register("serve", "/lookup", handle)
    client = LocalChannel(net, "c:1")
    hashes = np.random.default_rng(3).integers(0, 2**32, 512, dtype=np.uint32)

    plain_rows, _ = _run(
        BatchForwarder(client).forward_batch("s:1", hashes)
    )
    fab_rows, _ = _run(
        BatchForwarder(client, fabric_arrays=True).forward_batch("s:1", hashes)
    )
    assert np.array_equal(plain_rows, fab_rows)


def test_tcp_channel_wire_accounting():
    """TCPChannel counts every frame it writes, both roles — the
    fabric's wire_stats contract on the serve framing."""

    async def main():
        server = TCPChannel(app="srv")
        server.register("svc", "/echo", lambda b, h: {"x": b.get("x")})
        addr = await server.listen("127.0.0.1", 0)
        client = TCPChannel(app="cli")
        for i in range(3):
            await client.call(addr, "svc", "/echo", {"x": i}, timeout=5)
        cs, ss = client.wire_stats(), server.wire_stats()
        await client.close()
        await server.close()
        assert cs["frames_sent"] == 3 and ss["frames_sent"] == 3
        assert cs["bytes_sent"] > 0 and ss["bytes_sent"] > 0

    _run(main())


def test_tcp_channel_wire_stats_race_free_under_concurrent_senders():
    """Many concurrent in-flight calls (the multiplexed-by-id pool) with
    a poller sampling ``wire_stats()`` between completions: every sample
    monotone non-decreasing, and the final totals exact — frames_sent
    equals the call count on both roles and bytes_sent equals the sum of
    the frames actually written (r20 obs satellite)."""

    async def main():
        server = TCPChannel(app="srv")

        async def handle(body, headers):
            await asyncio.sleep(0.001 * (body.get("x", 0) % 4))
            return {"x": body.get("x")}

        server.register("svc", "/echo", handle)
        addr = await server.listen("127.0.0.1", 0)
        client = TCPChannel(app="cli")
        samples = []
        stop = asyncio.Event()

        async def poll():
            while not stop.is_set():
                samples.append((client.wire_stats(), server.wire_stats()))
                await asyncio.sleep(0.001)

        poller = asyncio.ensure_future(poll())
        n = 64
        results = await asyncio.gather(
            *(client.call(addr, "svc", "/echo", {"x": i}, timeout=10)
              for i in range(n))
        )
        stop.set()
        await poller
        cs, ss = client.wire_stats(), server.wire_stats()
        await client.close()
        await server.close()
        assert sorted(r["x"] for r in results) == list(range(n))
        samples.append((cs, ss))
        for (pc, ps), (cc, cs_) in zip(samples, samples[1:]):
            for prev, cur in ((pc, cc), (ps, cs_)):
                assert cur["frames_sent"] >= prev["frames_sent"]
                assert cur["bytes_sent"] >= prev["bytes_sent"]
        assert cs["frames_sent"] == n and ss["frames_sent"] == n
        assert cs["bytes_sent"] > 0 and ss["bytes_sent"] > 0

    _run(main())
