"""Golden wire-conformance tests (VERDICT round-1 item 5).

The reference's tier-3 suite proves cross-implementation conformance by
running a shared harness against real processes
(``test/run-integration-tests:99-113``).  TChannel interop is out of scope
here, so the achievable substitute is a recorded corpus: canonical JSON
bodies hand-derived from the reference's serialization semantics
(``swim/ping_sender.go:35-40``, ``ping_request_sender.go:35-41``,
``ping_request_handler.go:26-30``, ``join_sender.go:58-63``,
``join_handler.go:27-32``, ``member.go:135-167``, ``memberlist.go:106-128``)
replayed through this implementation's codecs and live host-plane handlers
in both directions.  These tests pin the wire schema independently of the
encoder: if a codec key, state string, unit, or shim drifts, a frozen
literal — not a round-trip identity — catches it.
"""

from __future__ import annotations

import asyncio
import json
from pathlib import Path

import pytest

from ringpop_tpu.hashing import fingerprint32
from ringpop_tpu.net import LocalNetwork
from ringpop_tpu.swim.join import JoinRequest, JoinResponse, handle_join
from ringpop_tpu.swim.member import Change, state_id
from ringpop_tpu.swim.memberlist import Memberlist
from ringpop_tpu.swim.ping import Ping, handle_ping
from ringpop_tpu.swim.ping_request import PingRequest, PingResponse

from tests.swim_utils import bootstrap_nodes, make_nodes

CORPUS = json.loads(
    (Path(__file__).parent / "golden" / "wire_corpus.json").read_text()
)


# -- Change codec: every state, both shims, both directions -----------------


@pytest.mark.parametrize("case", CORPUS["changes"], ids=lambda c: c["name"])
def test_change_decode_matches_golden(case):
    c = Change.from_wire(case["wire"])
    want = case["decoded"]
    assert c.address == want["address"]
    assert c.incarnation == want["incarnation"]
    assert c.status == want["status"]
    assert c.source == want["source"]
    assert c.source_incarnation == want["source_incarnation"]
    assert c.timestamp == want["timestamp"]


@pytest.mark.parametrize("case", CORPUS["changes"], ids=lambda c: c["name"])
def test_change_reencode_is_identical(case):
    """Decode → encode must reproduce the reference body byte-for-byte as a
    dict: the tombstone shim re-applies on the way out (member.go:159-167)
    and unknown statuses pass through verbatim (member.go:124-127)."""
    assert Change.from_wire(case["wire"]).to_wire() == case["wire"]


def test_change_encode_from_fields_matches_golden():
    """Construct from plain fields (no decode step) → golden body."""
    case = next(c for c in CORPUS["changes"] if c["name"] == "tombstone_shimmed")
    d = case["decoded"]
    c = Change(
        address=d["address"],
        incarnation=d["incarnation"],
        status=d["status"],
        source=d["source"],
        source_incarnation=d["source_incarnation"],
        timestamp=d["timestamp"],
    )
    assert c.to_wire() == case["wire"]


# -- message bodies ---------------------------------------------------------


def test_ping_body_roundtrip():
    wire = CORPUS["ping_request"]["wire"]
    p = Ping.from_wire(wire)
    assert p.source == wire["source"]
    assert p.checksum == wire["checksum"]
    assert p.source_incarnation == wire["sourceIncarnationNumber"]
    assert p.to_wire() == wire


def test_ping_req_bodies_roundtrip():
    wire = CORPUS["ping_req_request"]["wire"]
    pr = PingRequest.from_wire(wire)
    assert pr.target == wire["target"]
    assert pr.to_wire() == wire

    rwire = CORPUS["ping_req_response"]["wire"]
    res = PingResponse.from_wire(rwire)
    assert res.ok is True and res.target == rwire["target"]
    assert res.to_wire() == rwire


def test_join_request_roundtrip_and_duration_unit():
    """The reference's joinRequest.Timeout is a Go time.Duration: integer
    nanoseconds on the wire (join_sender.go:58-63)."""
    wire = CORPUS["join_request"]["wire"]
    req = JoinRequest.from_wire(wire)
    assert req.timeout == CORPUS["join_request"]["decoded_timeout_seconds"]
    assert req.to_wire() == wire


def test_join_response_roundtrip():
    wire = CORPUS["join_response"]["wire"]
    res = JoinResponse.from_wire(wire)
    assert res.coordinator == wire["coordinator"]
    assert res.checksum == wire["membershipChecksum"]
    # tombstone shim inside a membership list lifts and re-applies
    assert res.membership[1].status == state_id("tombstone")
    assert res.to_wire() == wire


# -- checksum canonical form ------------------------------------------------


class _StubNode:
    """Just enough node for a standalone Memberlist."""

    address = "stub:0"

    def emit(self, event):
        pass

    def handle_changes(self, changes):
        pass

    def stopped(self) -> bool:
        return False

    class rollup:
        @staticmethod
        def track_updates(changes):
            pass


@pytest.mark.parametrize("case", CORPUS["checksum_strings"], ids=lambda c: c["name"])
def test_checksum_string_matches_golden(case):
    ml = Memberlist(_StubNode())
    for m in case["members"]:
        status = state_id(m["status"])
        if m["status"] == "tombstone":
            # first-seen tombstones are refused (memberlist tombstone rule);
            # arrive as faulty first, then lift via the wire shim
            ml.update([Change(m["address"], m["incarnation"], state_id("faulty"))])
            ml.update(
                [
                    Change.from_wire(
                        {
                            "address": m["address"],
                            "incarnationNumber": m["incarnation"],
                            "status": "faulty",
                            "tombstone": True,
                        }
                    )
                ]
            )
        else:
            ml.update([Change(m["address"], m["incarnation"], status)])
    assert ml.gen_checksum_string() == case["canonical"]
    assert ml.compute_checksum() == case["farm32"]
    assert fingerprint32(case["canonical"]) == case["farm32"]


# -- live host-plane replay -------------------------------------------------


def test_golden_ping_replays_through_live_handler():
    """Feed the recorded reference ping body to a bootstrapped node's real
    handler: the piggybacked change must apply and the response must carry
    exactly the reference's response schema."""

    async def run():
        nodes = make_nodes(2)
        await bootstrap_nodes(nodes)
        node = nodes[0]
        body = CORPUS["ping_request"]["wire"]
        res = await handle_ping(node, body, {})
        # response schema: the same `ping` struct (ping_sender.go:35-40)
        assert set(res) == {"changes", "checksum", "source", "sourceIncarnationNumber"}
        assert res["source"] == node.address
        # the golden body's alive change was applied through the full
        # update pipeline (first-seen applies wholesale)
        m = node.memberlist.member("10.0.0.2:3000")
        assert m is not None and m.status == state_id("alive")
        assert m.incarnation == body["changes"][0]["incarnationNumber"]
        for nd in nodes:
            nd.destroy()

    asyncio.run(run())


def test_golden_join_replays_through_live_handler():
    async def run():
        nodes = make_nodes(2, app="testapp")
        await bootstrap_nodes(nodes)
        node = nodes[0]
        res = await handle_join(node, CORPUS["join_request"]["wire"], {})
        # response schema per join_handler.go:27-32
        assert set(res) == {"app", "coordinator", "membership", "membershipChecksum"}
        assert res["app"] == "testapp"
        assert res["coordinator"] == node.address
        addrs = {c["address"] for c in res["membership"]}
        assert {n.address for n in nodes} <= addrs
        for c in res["membership"]:
            assert set(c) >= {
                "source",
                "sourceIncarnationNumber",
                "address",
                "incarnationNumber",
                "status",
                "timestamp",
            }
            assert isinstance(c["status"], str)
        for nd in nodes:
            nd.destroy()

    asyncio.run(run())


def test_golden_join_rejects_wrong_app():
    async def run():
        nodes = make_nodes(2, app="otherapp")
        await bootstrap_nodes(nodes)
        with pytest.raises(ValueError, match="different app"):
            await handle_join(nodes[0], CORPUS["join_request"]["wire"], {})
        for nd in nodes:
            nd.destroy()

    asyncio.run(run())
