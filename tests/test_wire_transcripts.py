"""Golden wire-CONVERSATION regression (VERDICT round-2 item 5).

Replays the scripted multi-frame scenes from
``capture_wire_transcripts.py`` against live host-plane nodes and asserts
the recorded frame sequences — order, endpoints, and full request AND
response bodies — reproduce exactly.  A drift in any handler's *sequence*
behavior (full-sync trigger condition, reverse-full-sync initiation, join
fan-out, heal's reincarnation-before-merge) fails here even if every
individual body still round-trips.

Reference analog: the tier-3 conversation-level conformance runs
(``test/run-integration-tests:99-113``; sequences under test:
``swim/disseminator.go:156-304``, ``swim/join_sender.go:281-435``,
``swim/heal_partition.go:33-124``).
"""

from __future__ import annotations

import asyncio
import json
from pathlib import Path

import pytest

from tests.capture_wire_transcripts import GOLDEN_PATH, SCENES

GOLDEN = json.loads(Path(GOLDEN_PATH).read_text())

# every scene must exercise the endpoints its reference call stack names
_EXPECTED_ENDPOINTS = {
    "ping_piggyback": [("/protocol/ping", None)],
    "full_sync_reverse": [("/protocol/ping", None), ("/protocol/join", None)],
    "join_round": [("/protocol/join", None), ("/protocol/join", None)],
    "heal_reincarnate": [("/protocol/join", None), ("/protocol/ping", None)],
}


@pytest.mark.parametrize("name", sorted(SCENES), ids=sorted(SCENES))
def test_conversation_replays_bit_identical(name):
    got = asyncio.run(SCENES[name]())
    want = GOLDEN[name]
    assert [f["endpoint"] for f in got] == [f["endpoint"] for f in want], (
        f"{name}: frame sequence changed"
    )
    for i, (g, w) in enumerate(zip(got, want)):
        assert g == w, f"{name}: frame {i} ({w['endpoint']}) drifted"
    assert len(got) == len(want)


@pytest.mark.parametrize("name", sorted(SCENES), ids=sorted(SCENES))
def test_scene_covers_expected_endpoints(name):
    """Guard against a scene silently degenerating (e.g. the full-sync
    branch no longer triggering, leaving only a plain ping recorded)."""
    eps = [f["endpoint"] for f in GOLDEN[name]]
    assert eps == [e for e, _ in _EXPECTED_ENDPOINTS[name]]


def test_full_sync_response_carries_whole_membership():
    """The recorded full-sync reply must contain B's entire view including
    the silently-added member — that's what makes it a full sync and not a
    piggyback reply (disseminator.go:168-181)."""
    ping = GOLDEN["full_sync_reverse"][0]
    assert ping["request"]["changes"] == []  # the trigger: no changes
    addrs = {c["address"] for c in ping["response"]["changes"]}
    assert "127.0.0.1:3999" in addrs and len(addrs) == 3


def test_heal_ping_reasserts_via_suspects():
    """The heal merge's ping must carry Suspect declarations for the
    members that would otherwise stay unpingable after the merge
    (heal_partition.go:64-108)."""
    ping = GOLDEN["heal_reincarnate"][1]
    statuses = {c["status"] for c in ping["request"]["changes"]}
    assert statuses == {"suspect"}
