"""Accelerator (real-TPU) test suite — lives OUTSIDE tests/ on purpose.

tests/conftest.py pins the whole pytest process to the CPU backend before
jax initializes (the virtual 8-device mesh recipe), so hardware tests
cannot share that process.  This suite runs via ``make test-accel`` in its
own process, probes the axon tunnel in a SUBPROCESS first (a wedged tunnel
hangs jax init rather than raising — see ringpop_tpu/util/accel.py), and
skips everything cleanly when no live accelerator is reachable.
"""

import pytest

from ringpop_tpu.util.accel import configure_compile_cache, probe_accelerator

_PROBE = None


def _probe():
    global _PROBE
    if _PROBE is None:
        _PROBE = probe_accelerator(timeouts_s=(90.0,))
    return _PROBE


def pytest_collection_modifyitems(config, items):
    probe = _probe()
    if probe["alive"] and probe.get("platform") not in ("cpu", None):
        # persistent fingerprinted compile cache (shared default base): a
        # repeat run in this window — or the next — pays zero recompiles.
        # Only AFTER a live probe: the fingerprint touches jax.devices(),
        # which HANGS (not raises) on a wedged tunnel, and this suite's
        # whole design is to never let that hang reach the main process.
        configure_compile_cache()
        return
    if probe["alive"]:
        reason = f"backend is {probe.get('platform')!r}, not an accelerator"
    else:
        reason = f"no live accelerator: {probe['reason']}"
    skip = pytest.mark.skip(reason=reason)
    for item in items:
        item.add_marker(skip)
