"""Real-hardware smoke suite: the on-device kernels compiled for the TPU.

The CPU suite already proves bit-exactness of every kernel under the CPU
backend (and the Pallas kernel under interpret mode); what it cannot prove
is that Mosaic/XLA:TPU actually lowers them.  These tests close that gap —
they are the accelerator analog of the reference's micro-benches
(``hashring/hashring_test.go:332``, ``rbtree_test.go:640-672``) plus one
flagship-model step on hardware.

Runs via ``make test-accel``; auto-skips when the axon tunnel is down.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from ringpop_tpu.hashing.farm import fingerprint32, pack_strings  # noqa: E402
from ringpop_tpu.ops.hash_ops import fingerprint32_device, keyed_owner_lookup  # noqa: E402
from ringpop_tpu.ops.hash_pallas import fingerprint32_pallas  # noqa: E402
from ringpop_tpu.ops.ring_ops import build_ring_tokens, ring_lookup, ring_lookup_n  # noqa: E402


def _corpus(seed=0):
    rng = np.random.default_rng(seed)
    strings = []
    for length in list(range(0, 26)) + [30, 41, 61, 99, 120, 127]:
        for _ in range(3):
            strings.append(bytes(rng.integers(0, 256, size=length, dtype=np.uint8)))
    strings += [f"10.3.{i % 256}.{i % 40}:31{i % 100:02d}#{i}".encode() for i in range(512)]
    return strings


def test_device_hash_bitexact_on_accel():
    strings = _corpus(seed=11)
    mat, lens = pack_strings(strings)
    got = np.asarray(fingerprint32_device(mat, lens))
    want = np.array([fingerprint32(s) for s in strings], dtype=np.uint32)
    assert (got == want).all()


def test_pallas_hash_compiled_bitexact():
    # interpret=False: this is the actual Mosaic lowering the advisor flagged
    # as unproven in round 1
    strings = _corpus(seed=12)
    mat, lens = pack_strings(strings)
    got = np.asarray(fingerprint32_pallas(mat, lens, interpret=False))
    want = np.array([fingerprint32(s) for s in strings], dtype=np.uint32)
    assert (got == want).all()


def test_keyed_owner_lookup_matches_host_ring():
    from ringpop_tpu.hashring import HashRing

    servers = [f"10.0.{i // 256}.{i % 256}:3000" for i in range(64)]
    ring = HashRing(replica_points=10)
    for s in servers:
        ring.add_server(s)
    keys = [f"user:{i}" for i in range(2000)]
    mat, lens = pack_strings([k.encode() for k in keys])
    tokens, owners = build_ring_tokens(servers, 10)
    got = np.asarray(keyed_owner_lookup(tokens, owners, jnp.asarray(mat), jnp.asarray(lens)))
    want = [ring.lookup(k) for k in keys]
    assert [servers[i] for i in got] == want


def test_ring_lookup_n_exact_on_accel():
    from ringpop_tpu.hashring import HashRing

    servers = [f"10.1.{i // 256}.{i % 256}:3000" for i in range(48)]
    ring = HashRing(replica_points=3)  # sparse ring stresses the rescan path
    for s in servers:
        ring.add_server(s)
    keys = [f"acct:{i}" for i in range(500)]
    hashes = jnp.asarray(
        np.array([fingerprint32(k.encode()) for k in keys], dtype=np.uint32)
    )
    tokens, owners = build_ring_tokens(servers, 3)
    got = np.asarray(ring_lookup_n(tokens, owners, hashes, 5, len(servers)))
    for row, k in zip(got, keys):
        want = ring.lookup_n(k, 5)
        assert [servers[i] for i in row if i >= 0] == want


def test_ring_lookup_throughput_sane():
    servers = [f"10.0.{i // 256}.{i % 256}:3000" for i in range(1024)]
    tokens, owners = build_ring_tokens(servers, 100)
    hashes = jnp.asarray(
        np.random.default_rng(0).integers(0, 2**32, size=200_000, dtype=np.uint32)
    )
    out = ring_lookup(tokens, owners, hashes)
    jax.block_until_ready(out)
    o = np.asarray(out)
    assert o.min() >= 0 and o.max() < len(servers)


def test_lifecycle_step_on_accel():
    from ringpop_tpu.sim import lifecycle
    from ringpop_tpu.sim.delta import DeltaFaults

    n = 4096
    sim = lifecycle.LifecycleSim(n=n, k=64, seed=0, suspect_ticks=4)
    up = np.ones(n, bool)
    up[7] = False
    faults = DeltaFaults(up=jnp.asarray(up))
    ticks, ok = sim.run_until_detected(
        np.array([7]), faults, max_ticks=256, check_every=16
    )
    assert ok, f"victim never detected within {ticks} ticks"
    assert ticks <= 256
    # on-device convergence queries compile and agree on hardware too
    assert bool(lifecycle.detection_complete(sim.state, [7], faults))
    # run on until in-flight rumors fold, then every live view must agree
    for _ in range(40):
        if bool(lifecycle.checksums_converged(sim.state, faults)):
            break
        sim.run(16, faults)
    assert bool(lifecycle.checksums_converged(sim.state, faults))
    cs = np.asarray(lifecycle.view_checksums(sim.state, faults))
    assert len(np.unique(cs[up])) == 1


def test_delta_convergence_on_accel():
    from ringpop_tpu.sim.delta import DeltaSim

    sim = DeltaSim(n=50_000, k=64, seed=0)
    ticks, ok = sim.run_until_converged(max_ticks=1024)
    assert ok and ticks <= 1024


def test_sparse_topk_bitexact_on_accel():
    """The sparse candidate selection (``lifecycle._top_m_sparse`` —
    prefix-sum compress + top_k + cond overflow fallback) must lower on
    the accelerator AND stay bit-identical to the dense ``lax.top_k``
    there: TPU sorts, scatters with out-of-range drops, and batched conds
    all have their own lowering paths, and the CPU suite cannot vouch for
    them.  Shapes are chosen above the static MIN_N floor so the sparse
    path actually engages."""
    from ringpop_tpu.sim import lifecycle

    cap, min_n = lifecycle._SPARSE_TOPK_CAP, lifecycle._SPARSE_TOPK_MIN_N
    # derive n from BOTH static-guard constants, so tuning either one can
    # never silently park every case on the dense path; n_cand likewise
    # tracks cap so "compressed" stays compressed and "overflow" overflows
    n, m = max(131072, min_n * 2, cap * 2), 64
    assert n > max(cap, min_n), "sparse path must engage at this n"
    sparse_f = jax.jit(lambda c: lifecycle._top_m_sparse(c, m))
    dense_f = jax.jit(lambda c: tuple(jax.lax.top_k(c, m)))
    rng = np.random.default_rng(5)
    for n_cand, tag in ((0, "empty"), (max(cap // 4, m + 1), "compressed"),
                        (cap + 512, "overflow")):
        cand = np.full(n, -1, np.int32)
        if n_cand:
            idx = np.sort(rng.choice(n, n_cand, replace=False))
            cand[idx] = rng.integers(0, 8, n_cand).astype(np.int32)  # ties
        c = jnp.asarray(cand)
        got_v, got_i = sparse_f(c)
        exp_v, exp_i = dense_f(c)
        assert np.array_equal(np.asarray(got_v), np.asarray(exp_v)), tag
        real = np.asarray(exp_v) >= 0
        assert np.array_equal(
            np.asarray(got_i)[real], np.asarray(exp_i)[real]
        ), tag
